"""Dispatch microbench: host overhead around the one compiled step.

The tentpole claim of the TPU design is that the whole block fuses into
one XLA computation — so on a SMALL model the step time is dominated by
the eager Python the Executor runs *around* that computation: the
per-step program/state rescans, the DP-mode re-`device_put` of every
parameter, and the blocking fetch. This bench measures exactly that
host cost, A/B-ing the dispatch fast path (prepared runners + resident
DP state + async fetches) against the legacy per-step path
(`FLAGS_executor_fast_path=0` + blocking `np.asarray` fetch — the
pre-ISSUE-2 behavior, kept as a flag precisely so this A/B stays
honest). The model is deep-and-narrow (many parameters, trivial
FLOPs) so the host bookkeeping dominates the way it does around a real
multi-hundred-parameter model.

Prints JSON lines (bench.py conventions, best-window timing via its
shared `_timed_steps` harness):

- ``dispatch_host_ms_per_step_dp``: the headline — data-parallel
  fast-path async ms/step (value) vs ``legacy_ms``; legacy re-puts
  every state leaf on the mesh every step, the fast path keeps state
  resident.
- ``dispatch_host_ms_per_step``: same A/B on one device (no DP
  re-puts; isolates the rescan + blocking-fetch overhead).
- ``dispatch_span_ms``: per-span breakdown from the RecordEvent
  instrumentation inside Executor.run (prepare / dispatch / fetch).

Usage: python bench_dispatch.py [steps_per_window]
       python bench.py dispatch [steps_per_window]
"""

import json
import os
import sys

import numpy as np

from bench import _timed_steps

# the DP A/B needs a multi-device mesh; on a CPU host carve 8 virtual
# devices (must happen before jax imports)
if "cpu" in os.environ.get("JAX_PLATFORMS", "cpu"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

DEPTH = 48      # deep and narrow: many state vars, trivial compute
HIDDEN = 8
BATCH = 16


def _build_program(pt):
    main, startup = pt.Program(), pt.Program()
    with pt.static.program_guard(main, startup):
        x = pt.static.data("x", shape=[HIDDEN])
        y = pt.static.data("y", shape=[1])
        h = x
        for i in range(DEPTH):
            h = pt.layers.fc(h, size=HIDDEN, param_attr=f"w{i}",
                             bias_attr=f"b{i}", act="relu")
        pred = pt.layers.fc(h, size=1, param_attr="w_out",
                            bias_attr="b_out")
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.Momentum(0.02, momentum=0.9).minimize(loss)
    return main, startup, loss


def main():
    # also reachable as `python bench.py dispatch [steps]` — take the
    # first numeric argv (skipping the mode word)
    argn = [a for a in sys.argv[1:] if a.lstrip("-").isdigit()]
    steps = int(argn[0]) if argn else \
        int(os.environ.get("BENCH_DISPATCH_STEPS", "200"))

    import jax

    import paddle_tpu as pt
    from paddle_tpu import profiler
    from paddle_tpu.static.executor import Scope, scope_guard

    dev = jax.devices()[0]
    pt.enable_static()
    rs = np.random.RandomState(0)
    xb = rs.randn(BATCH, HIDDEN).astype(np.float32)
    yb = rs.randn(BATCH, 1).astype(np.float32)

    def make_exe(dp):
        main, startup, loss = _build_program(pt)
        exe = pt.static.Executor()
        exe.run(startup)
        prog = main
        if dp:
            # places=2: enough devices that legacy's per-leaf re-put on
            # the mesh is exercised, few enough that the virtual-device
            # SPMD compute (host threads on CPU) doesn't drown the
            # host-overhead signal being measured
            prog = pt.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, places=min(2, len(jax.devices())))
        return exe, prog, loss

    import time

    class _Mode:
        """One timed configuration: its own executor + scope, warmed
        once; windows run on demand so fast/legacy windows INTERLEAVE
        (back-to-back pairs see the same ambient host load — a drifting
        shared CI box would otherwise bias whichever mode ran last).

        host_ms is what the TRAIN LOOP THREAD pays per step — the
        ISSUE's metric. In async mode (return_numpy=False) that is
        dispatch only: the window issues all N steps, the timer splits
        before the sync, and the device pipeline drains the rest
        (steps N+1.. dispatch while step N computes; on a synchronous
        CPU backend dispatch == total). In blocking mode every step
        materializes its fetch — exactly what the pre-change loop
        paid."""

        def __init__(self, fast, return_numpy, dp):
            self.fast = fast
            self.return_numpy = return_numpy
            self.scope = Scope()
            with scope_guard(self.scope):
                self.exe, self.prog, self.loss = make_exe(dp)
            self.hosts, self.totals = [], []
            self._window(4)                     # compile + warm

        def _window(self, n):
            pt.set_flags({"executor_fast_path": self.fast})
            try:
                with scope_guard(self.scope):
                    t0 = time.perf_counter()
                    for _ in range(n):
                        lv = self.exe.run(
                            self.prog, feed={"x": xb, "y": yb},
                            fetch_list=[self.loss],
                            return_numpy=self.return_numpy)[0]
                    t_dispatch = time.perf_counter() - t0
                    # drain: the loss depends on the donated state
                    # chain, so fetching it serializes queued steps
                    float(np.ravel(np.asarray(lv))[0])
                    t_total = time.perf_counter() - t0
            finally:
                pt.set_flags({"executor_fast_path": True})
            return t_dispatch, t_total

        def window(self):
            t_dispatch, t_total = self._window(steps)
            self.hosts.append(t_dispatch / steps * 1e3)
            self.totals.append(t_total / steps * 1e3)

    def bench_pair(dp, windows=10):
        """Interleaved fast/legacy windows, order alternating within
        each pair. A shared CI host's load drifts on the seconds scale,
        so a min- or mean-over-windows estimator lets one lucky quiet
        window decide a mode's number; adjacent windows see the SAME
        load, so the per-pair fast/legacy ratio is load-invariant and
        its median is the robust speedup estimate."""
        fast = _Mode(True, False, dp)
        legacy = _Mode(False, True, dp)
        for w in range(windows):
            first, second = (fast, legacy) if w % 2 == 0 \
                else (legacy, fast)
            first.window()
            second.window()
        return fast, legacy

    def _median(xs):
        return float(np.median(np.asarray(xs)))

    def bench_compiled_step():
        """The floor: the cached compiled step called directly with
        device-resident feeds — no Executor.run bookkeeping at all."""
        with scope_guard(Scope()) as scope:
            exe, prog, loss = make_exe(False)
            exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])
            (runner,) = exe._runners.values()
            feeds = {"x": jax.numpy.asarray(xb),
                     "y": jax.numpy.asarray(yb)}
            state = {n: scope.find_var(n) for n in runner.state_names
                     if scope.find_var(n) is not None}
            key = exe._base_key(prog.random_seed)

            def once(carry):
                fetches, new_state = runner.step(carry, feeds, key,
                                                 np.uint32(0))
                return new_state, fetches[0]

            return _timed_steps(once, state, steps)

    def span_breakdown(fast, return_numpy, dp):
        """Average RecordEvent spans inside Executor.run per step."""
        profiler.reset_profiler()
        pt.set_flags({"executor_fast_path": fast})
        try:
            with scope_guard(Scope()):
                exe, prog, loss = make_exe(dp)
                for _ in range(3):        # compile + prepare outside
                    exe.run(prog, feed={"x": xb, "y": yb},
                            fetch_list=[loss],
                            return_numpy=return_numpy)
                profiler.start_profiler()
                for _ in range(50):
                    exe.run(prog, feed={"x": xb, "y": yb},
                            fetch_list=[loss],
                            return_numpy=return_numpy)
                profiler.stop_profiler()
        finally:
            pt.set_flags({"executor_fast_path": True})
            profiler._active["on"] = False
        spans = {}
        agg = {}
        for name, _, dur, _tid, _args in profiler._events:
            tot, cnt = agg.get(name, (0.0, 0))
            agg[name] = (tot + dur, cnt + 1)
        for name, (tot, cnt) in agg.items():
            if name.startswith("executor.run/"):
                spans[name.split("/", 1)[1]] = round(tot / cnt * 1e3, 4)
        profiler.reset_profiler()
        return spans

    def report(metric, fast, legacy, extra):
        ratios = [f / l for f, l in zip(fast.hosts, legacy.hosts)]
        print(json.dumps({
            "metric": metric,
            "value": round(_median(fast.hosts), 4),
            "unit": "ms/step (host)",
            "legacy_ms": round(_median(legacy.hosts), 4),
            "improvement_pct": round((1.0 - _median(ratios)) * 100.0,
                                     1),
            "fast_device_ms": round(_median(fast.totals), 4),
            "legacy_device_ms": round(_median(legacy.totals), 4),
            "windows_fast": [round(h, 3) for h in fast.hosts],
            "windows_legacy": [round(h, 3) for h in legacy.hosts],
            "device": dev.platform,
            "steps_per_window": steps,
            **extra,
        }))

    # headline: single device — isolates the per-step rescan +
    # blocking-fetch overhead around the one compiled step
    sd_fast, sd_legacy = bench_pair(dp=False)
    floor = bench_compiled_step()
    report("dispatch_host_ms_per_step", sd_fast, sd_legacy,
           {"compiled_step_ms":
            round(floor.dt / floor.steps * 1e3, 4)})

    # data-parallel: legacy additionally re-puts every state leaf on
    # the mesh every step, fast keeps them resident (on a CPU host the
    # virtual-device SPMD compute shares the cores with the host
    # thread, so this ratio understates the TPU-side win)
    dp_fast, dp_legacy = bench_pair(dp=True)
    report("dispatch_host_ms_per_step_dp", dp_fast, dp_legacy,
           {"state_leaves": (DEPTH + 1) * 4})

    print(json.dumps({
        "metric": "dispatch_span_ms",
        "fast_dp": span_breakdown(True, False, dp=True),
        "legacy_dp": span_breakdown(False, True, dp=True),
    }))

    # tracing A/B + tail attribution (monitor/trace.py): ABBA-ordered
    # quadruples of SHORT windows with per-step trace trees on vs off
    # (keep-all, the worst case — every step's tree materializes).
    # The deep-narrow model makes the host path the step time, and the
    # ABBA micro-structure keeps both sides of each ratio inside the
    # same slice of this shared host's drifting load — long interleaved
    # windows measured the drift, not the tracing. The smoke test
    # asserts the trimmed-mean estimate (bench._abba_overhead) stays
    # < 1.05x; a keep-all pass then attributes the slowest decile of
    # steps to prepare/feed_stage/dispatch/fetch.
    from paddle_tpu.monitor import trace as mtrace
    pairs = int(os.environ.get("BENCH_DISPATCH_TRACE_PAIRS", "8"))
    twin = int(os.environ.get("BENCH_DISPATCH_TRACE_WIN", "12"))
    mode = _Mode(True, True, False)     # fast path, blocking fetch
    # overhead is measured at the DEFAULT tail-sampling policy — the
    # deployed configuration the <1.05x claim is about (keep-all
    # materializes every step's tree and measurably feeds the GC; the
    # attribution pass below pays that separately, untimed)
    mtrace.enable(sample_rate=0.05, slow_keep=8)
    mtrace.disable()

    def t_win(traced):
        if traced:
            mtrace.enable()
        else:
            mtrace.disable()
        _td, tt = mode._window(twin)
        return tt / twin * 1e3

    from bench import _abba_overhead
    t_win(True), t_win(False)           # warm both paths
    est, pair_ratios, on_ms, off_ms = _abba_overhead(t_win, pairs)
    mtrace.disable()
    print(json.dumps({
        "metric": "dispatch_trace_overhead_ratio",
        "value": round(est, 4), "unit": "x",
        "traced_ms_per_step": round(_median(on_ms), 4),
        "untraced_ms_per_step": round(_median(off_ms), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios],
        "steps_per_window": twin,
    }))
    # attribution pass: keep-all, UNTIMED — every step's tree lands in
    # the ring so the slowest decile attributes by measurement
    mtrace.enable(sample_rate=1.0, capacity=65536)
    for _w in range(4):
        mode._window(twin)
    mtrace.disable()
    roots = sorted((s for s in mtrace.spans()
                    if s["name"] == "executor/step"),
                   key=lambda s: -s["dur"])
    n_dec = max(1, len(roots) // 10)
    phases = ("prepare", "feed_stage", "dispatch", "fetch")
    shares = {k: [] for k in phases}
    for r in roots[:n_dec]:
        per = {}
        for s in mtrace.spans(r["trace"]):
            if s["span"] == 1:      # the root itself
                continue
            key = s["name"].split("/", 1)[1]
            per[key] = per.get(key, 0.0) + s["dur"]
        for k in phases:
            shares[k].append(per.get(k, 0.0) / r["dur"])
    print(json.dumps({
        "metric": "dispatch_p99_attribution",
        "value": round(float(np.percentile(
            [r["dur"] * 1e3 for r in roots], 99)), 4) if roots
        else None,
        "unit": "ms", "n_slowest": n_dec,
        **{f"{k}_share":
           (round(_median(v), 4) if v else None)
           for k, v in shares.items()},
    }))

    # memory-poller A/B (monitor/memory.py): the same ABBA protocol
    # with the live-buffer poller sampling at a deliberately hostile
    # 50 ms interval vs fully off (disable == zero recording). The
    # poller's work — jax.live_arrays aggregation — runs on its own
    # daemon thread, so what this measures is the GIL/allocator
    # shadow it casts over the dispatch hot path; the smoke test
    # asserts < 1.05x.
    from paddle_tpu.monitor import memory as _memory
    mem_pairs = int(os.environ.get("BENCH_DISPATCH_MEM_PAIRS", "8"))

    def m_win(polling):
        if polling:
            _memory.enable(interval=0.05)
        else:
            _memory.disable()
        _td, tt = mode._window(twin)
        return tt / twin * 1e3

    m_win(True), m_win(False)           # warm both paths
    est_m, pair_ratios_m, on_m, off_m = _abba_overhead(m_win,
                                                       mem_pairs)
    _memory.disable()
    print(json.dumps({
        "metric": "memory_overhead_ratio", "path": "dispatch",
        "value": round(est_m, 4), "unit": "x",
        "polled_ms_per_step": round(_median(on_m), 4),
        "unpolled_ms_per_step": round(_median(off_m), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios_m],
        "poll_interval_s": 0.05, "steps_per_window": twin,
    }))

    # goodput-ledger A/B (monitor/goodput.py): same ABBA protocol with
    # the ledger armed vs disarmed. Armed, every Executor.run pays
    # on_run_start/on_run_end (two perf_counter stamps + one
    # thread-local counter bump); disarmed it's a single module-global
    # check. The smoke test asserts < 1.05x — the always-on
    # attribution claim.
    from paddle_tpu.monitor import goodput as _goodput
    gp_pairs = int(os.environ.get("BENCH_DISPATCH_GOODPUT_PAIRS", "8"))

    def g_win(armed):
        if armed:
            _goodput.enable()
        else:
            _goodput.disable()
        _td, tt = mode._window(twin)
        return tt / twin * 1e3

    g_win(True), g_win(False)           # warm both paths
    est_g, pair_ratios_g, on_g, off_g = _abba_overhead(g_win,
                                                       gp_pairs)
    _goodput.disable()
    print(json.dumps({
        "metric": "goodput_overhead_ratio", "path": "dispatch",
        "value": round(est_g, 4), "unit": "x",
        "armed_ms_per_step": round(_median(on_g), 4),
        "disarmed_ms_per_step": round(_median(off_g), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios_g],
        "steps_per_window": twin,
    }))


if __name__ == "__main__":
    main()
