"""Framework flag system.

Analog of the reference's three-tier config (ref: SURVEY §5.6): C++ gflags
exported through env FLAGS_* strings
(ref: python/paddle/fluid/__init__.py __bootstrap__,
paddle/fluid/platform/init.cc:39). Here: a typed registry seeded from
``FLAGS_<name>`` environment variables, mutable at runtime via
``set_flags`` (same surface as fluid.set_flags).
"""

import os
import threading

_lock = threading.Lock()
_REGISTRY = {}


class _Flag:
    __slots__ = ("name", "value", "type", "help")

    def __init__(self, name, default, help=""):
        self.name = name
        self.type = type(default)
        self.help = help
        env = os.environ.get("FLAGS_" + name)
        self.value = self._parse(env) if env is not None else default

    def _parse(self, s):
        if self.type is bool:
            return s.lower() in ("1", "true", "yes", "on")
        return self.type(s)


def define_flag(name, default, help=""):
    with _lock:
        if name not in _REGISTRY:
            _REGISTRY[name] = _Flag(name, default, help)
    return _REGISTRY[name]


def get_flag(name):
    return _REGISTRY[name].value


def set_flags(flags_dict):
    """fluid.set_flags parity: {'FLAGS_x': v} or {'x': v}."""
    for k, v in flags_dict.items():
        name = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        if name not in _REGISTRY:
            define_flag(name, v)
        else:
            _REGISTRY[name].value = _REGISTRY[name].type(v)


class _FlagsView:
    """Attribute access: flags.paddle_num_threads."""

    def __getattr__(self, name):
        try:
            return get_flag(name)
        except KeyError:
            raise AttributeError(name)


flags = _FlagsView()

# Core flags (analogs of the reference's most-used gflags).
define_flag("paddle_num_threads", os.cpu_count() or 1,
            "Host threads for the data pipeline "
            "(ref: platform/init.cc:39 FLAGS_paddle_num_threads)")
define_flag("check_nan_inf", False,
            "Fuse isfinite sentinels into every compiled device "
            "segment and, on a trip, localize the first non-finite "
            "tensor/op by eager per-op replay (monitor/numerics.py, "
            "docs/DEBUGGING.md; ref: framework/operator.cc "
            "FLAGS_check_nan_inf)")
define_flag("benchmark", False, "Print per-step timing")
define_flag("reader_queue_capacity", 64,
            "Capacity of async feeding queues "
            "(ref: reader/lod_tensor_blocking_queue.h)")
define_flag("allocator_strategy", "xla",
            "Host staging allocator strategy "
            "(ref: memory/allocation/allocator_strategy.cc:19)")
