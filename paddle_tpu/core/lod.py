"""Ragged sequence batches — the TPU-native replacement for LoDTensor.

The reference threads variable-length sequence structure through every
sequence op as offset-based "level of detail" metadata attached to a dense
tensor (ref: paddle/fluid/framework/lod_tensor.h:110, offset doc :229).
That representation implies dynamic shapes, which XLA cannot tile onto the
MXU. The TPU-native design is **dense padding + explicit lengths/segment
ids** with static shapes:

- ``RaggedBatch``: data padded to [batch, max_len, ...] + ``lengths[batch]``.
- masks/segment ids derived on demand (``sequence_mask``) and fused by XLA
  into the consuming op.
- bucketing-by-length (the padding-waste mitigation) lives in the data
  pipeline, not the type: ``paddle_tpu.reader.bucketed_batch`` pads each
  batch to its bucket's boundary, so jit compiles one program per
  bucket instead of retracing per length.

A RaggedBatch is a JAX pytree, so it flows through jit/grad/shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class RaggedBatch:
    """Dense-padded batch of variable-length sequences.

    data:    [batch, max_len, ...] padded values
    lengths: [batch] int32 valid lengths
    """

    def __init__(self, data, lengths):
        self.data = data
        self.lengths = lengths

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        # multi-level LoD structure (set by fluid.create_lod_tensor)
        # rides in aux so jit/grad/device_put don't drop it; a different
        # LoD structure is a different treedef — which is right, since
        # it describes different batch structure
        rsl = getattr(self, "recursive_seq_lens", None)
        aux = (tuple(tuple(l) for l in rsl)
               if rsl is not None else None)
        return (self.data, self.lengths), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        rb = cls(*children)
        if aux is not None:
            rb.recursive_seq_lens = [list(l) for l in aux]
        return rb

    # -- construction ------------------------------------------------------
    @classmethod
    def from_list(cls, seqs, max_len=None, dtype=None, pad_value=0):
        """Build from a python list of per-sequence numpy arrays/lists."""
        seqs = [np.asarray(s) for s in seqs]
        lengths = np.array([len(s) for s in seqs], dtype=np.int32)
        max_len = int(max_len or (lengths.max() if len(seqs) else 0))
        tail = seqs[0].shape[1:] if seqs else ()
        dtype = dtype or (seqs[0].dtype if seqs else np.float32)
        out = np.full((len(seqs), max_len) + tail, pad_value, dtype=dtype)
        for i, s in enumerate(seqs):
            out[i, : len(s)] = s[:max_len]
        return cls(jnp.asarray(out), jnp.asarray(lengths))

    @classmethod
    def from_lod(cls, flat_data, lod, max_len=None):
        """Compat shim: build from the reference's (flat values, offsets)
        representation (ref: lod_tensor.h:229 offset-based LoD)."""
        flat_data = np.asarray(flat_data)
        offsets = np.asarray(lod[-1] if isinstance(lod[0], (list, tuple, np.ndarray)) else lod)
        seqs = [flat_data[offsets[i]: offsets[i + 1]]
                for i in range(len(offsets) - 1)]
        return cls.from_list(seqs, max_len=max_len)

    # -- views -------------------------------------------------------------
    @property
    def batch_size(self):
        return self.data.shape[0]

    @property
    def max_len(self):
        return self.data.shape[1]

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def mask(self, dtype=jnp.float32):
        """[batch, max_len] 1/0 validity mask."""
        pos = jnp.arange(self.max_len, dtype=jnp.int32)[None, :]
        return (pos < self.lengths[:, None]).astype(dtype)

    def segment_ids(self):
        """Flat [batch*max_len] ids, padding marked with batch index too —
        combine with mask for segment reductions."""
        return jnp.repeat(jnp.arange(self.batch_size, dtype=jnp.int32),
                          self.max_len)

    def to_lod(self):
        """Back-compat: (flat concatenated values, offsets)."""
        lens = np.asarray(self.lengths)
        data = np.asarray(self.data)
        flat = np.concatenate([data[i, : lens[i]] for i in range(len(lens))],
                              axis=0) if len(lens) else data.reshape((0,) + data.shape[2:])
        offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        return flat, [offsets.tolist()]

    def __repr__(self):
        return (f"RaggedBatch(shape={tuple(self.data.shape)}, "
                f"dtype={self.data.dtype}, lengths={self.lengths})")


def sequence_mask(lengths, maxlen=None, dtype=jnp.float32):
    """fluid.layers.sequence_mask parity (ref: python/paddle/fluid/layers/
    nn.py sequence_mask)."""
    lengths = jnp.asarray(lengths)
    if maxlen is None:
        raise ValueError("maxlen must be static under jit; pass it explicitly")
    pos = jnp.arange(maxlen, dtype=lengths.dtype)
    return (pos[None, :] < lengths[:, None]).astype(dtype)
