"""Dtype registry.

Analog of the reference's VarType dtype enum
(ref: paddle/fluid/framework/framework.proto:105-162) and the software
float16 type (ref: paddle/fluid/platform/float16.h). On TPU, bfloat16 is
the first-class reduced-precision type (MXU-native); fp16 is kept for
compatibility.
"""

import jax.numpy as jnp
import numpy as np

float32 = jnp.float32
float64 = jnp.float64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_

_STR_TO_DTYPE = {
    "float32": float32, "fp32": float32,
    "float64": float64, "double": float64, "fp64": float64,
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8,
    "bool": bool_,
}

FLOATING = (float16, bfloat16, float32, float64)
INTEGER = (int8, int16, int32, int64, uint8)


def convert_dtype(dtype):
    """Normalize a string/numpy/jnp dtype spec to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _STR_TO_DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return _STR_TO_DTYPE[key]
    return jnp.dtype(dtype).type


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def dtype_name(dtype):
    return jnp.dtype(dtype).name


def numpy_dtype(dtype):
    return np.dtype(jnp.dtype(convert_dtype(dtype)))
