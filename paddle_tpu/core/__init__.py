"""Core data model: dtypes, places, flags, diagnostics, ragged metadata.

TPU-native analog of the reference's layer 0/1
(paddle/fluid/platform + paddle/fluid/framework core data model).
"""

from paddle_tpu.core import dtypes
from paddle_tpu.core import enforce
from paddle_tpu.core import flags
from paddle_tpu.core import place
from paddle_tpu.core import lod
from paddle_tpu.core.enforce import EnforceNotMet, EOFException  # noqa: F401
# fluid.core.EOFException is the reader-protocol loop terminator; users
# catch it as core.EOFException, so expose it here
