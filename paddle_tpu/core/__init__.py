"""Core data model: dtypes, places, flags, diagnostics, ragged metadata.

TPU-native analog of the reference's layer 0/1
(paddle/fluid/platform + paddle/fluid/framework core data model).
"""

from paddle_tpu.core import dtypes
from paddle_tpu.core import enforce
from paddle_tpu.core import flags
from paddle_tpu.core import place
from paddle_tpu.core import lod
from paddle_tpu.core import compile_cache
from paddle_tpu.core.enforce import EnforceNotMet, EOFException  # noqa: F401
# fluid.core.EOFException is the reader-protocol loop terminator; users
# catch it as core.EOFException, so expose it here

# persistent XLA compilation cache: PADDLE_TPU_CACHE_DIR in the
# environment (the elastic launcher sets it for workers) turns it on at
# import, before any jit compiles — a restarted worker's compiles then
# read the previous incarnation's on-disk entries instead of redoing XLA.
# Never fatal: a bad dir (read-only volume, typo) must degrade to a cold
# start, not crash every `import paddle_tpu` — under the elastic
# launcher that would burn the whole restart budget re-dying at import.
try:
    compile_cache.enable_from_env()
except Exception as _e:  # pragma: no cover - env-dependent
    import warnings as _warnings
    _warnings.warn(f"PADDLE_TPU_CACHE_DIR ignored "
                   f"(compilation cache disabled): {_e}")
