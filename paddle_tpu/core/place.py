"""Places — device tags.

Analog of platform::Place (ref: paddle/fluid/platform/place.h:26,37,52:
CPUPlace/CUDAPlace/CUDAPinnedPlace). The TPU-native build replaces
CUDAPlace with TPUPlace; DeviceContext/stream management collapses into
XLA's runtime (there is no per-op stream bookkeeping when the whole step is
one compiled computation), so a Place here simply names a `jax.Device`.
"""

import functools

import jax


class Place:
    """Base device tag; wraps a jax.Device."""

    device_kind = None

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices() if _matches(d, self)]
        if not devs:
            # fall back to any available device (e.g. CPUPlace under
            # tpu-only or TPUPlace under forced-cpu test runs)
            devs = jax.local_devices()
        return devs[min(self.device_id, len(devs) - 1)]


class CPUPlace(Place):
    device_kind = "cpu"


class TPUPlace(Place):
    device_kind = "tpu"


class CUDAPinnedPlace(CPUPlace):  # compat alias: pinned host staging
    pass


def _matches(dev, place):
    plat = dev.platform.lower()
    if place.device_kind == "cpu":
        return plat == "cpu"
    return plat != "cpu"  # any accelerator counts as the TPU place


def is_compiled_with_tpu():
    return any(d.platform.lower() != "cpu" for d in jax.devices())


# fluid compat: code written against the reference checks for CUDA
def is_compiled_with_cuda():
    return False


def default_place():
    return TPUPlace(0) if is_compiled_with_tpu() else CPUPlace(0)


def device_count():
    return len(jax.devices())


_current = {"device": None}


def set_device(device):
    """'tpu', 'cpu', 'tpu:0' — analog of paddle.set_device."""
    name, _, idx = device.partition(":")
    place = CPUPlace(int(idx or 0)) if name == "cpu" else TPUPlace(int(idx or 0))
    _current["device"] = place
    return place


def get_device():
    return _current["device"] or default_place()


@functools.lru_cache(maxsize=None)
def local_device_count():
    return jax.local_device_count()


def cpu_places(device_count=None):
    """fluid.cpu_places parity (the get_places op's python surface,
    ref operators/controlflow/get_places_op.cc): one CPUPlace per
    requested device (default: all visible)."""
    n = device_count or max(
        len([d for d in jax.devices() if d.platform == "cpu"]), 1)
    return [CPUPlace(i) for i in range(n)]


def tpu_places(device_ids=None):
    """TPU analog of fluid.cuda_places: one TPUPlace per chip."""
    if device_ids is None:
        device_ids = [d.id for d in jax.devices()
                      if d.platform != "cpu"] or [0]
    return [TPUPlace(i) for i in device_ids]


# fluid.cuda_places compat: on this framework the accelerator is a TPU
cuda_places = tpu_places
CUDAPlace = TPUPlace        # fluid.CUDAPlace scripts get the accelerator


def cuda_pinned_places(device_count=None):
    """fluid.cuda_pinned_places parity: pinned host staging places
    (host memory is the staging tier on TPU, CUDAPinnedPlace analog)."""
    n = device_count or max(len(jax.devices()), 1)
    return [CUDAPinnedPlace(i) for i in range(n)]
