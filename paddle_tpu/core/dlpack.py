"""DLPack interop (framework/dlpack_tensor.{h,cc} parity).

Zero-copy exchange with torch/numpy/other frameworks via the DLPack
protocol — jax arrays already speak it; this module pins the fluid-shaped
API names."""

import jax
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a device array as a DLPack capsule."""
    return jax.dlpack.to_dlpack(jnp.asarray(x))


def from_dlpack(capsule_or_tensor):
    """Import from a DLPack capsule or any __dlpack__-capable tensor
    (torch.Tensor, numpy array, ...)."""
    return jax.dlpack.from_dlpack(capsule_or_tensor)
