"""Persistent XLA compilation cache (warm restarts).

The elastic launcher (distributed/launch.py) made restarts routine —
a preempted or crashed worker comes back seconds later — but every
incarnation used to recompile every jitted step from zero. This module
wires jax's on-disk compilation cache so a restarted process compiles
against the previous incarnation's cache entries: the retrace is
Python-cheap, and the XLA compile (the seconds-to-minutes part) becomes
a disk read.

Activation, in priority order:

- ``PADDLE_TPU_CACHE_DIR`` env var (read at ``paddle_tpu.core`` import,
  i.e. any ``import paddle_tpu``) — the launcher sets it for workers
  (default: ``<log_dir>/xla_cache``) so restarted ranks inherit it;
- an explicit ``enable(dirname)`` call — ``CheckpointManager`` calls
  this with ``<checkpoint_dir>/xla_cache`` as the default home, pairing
  "checkpoint often, restart anywhere" with "never recompile what an
  earlier incarnation compiled".

``stats()`` exposes hit/miss/request counters fed by jax's monitoring
events; ``paddle_tpu.profiler`` surfaces them in its summary so a warm
restart is verifiable (hits > 0), not vibes.
"""

import os
import threading

from paddle_tpu.monitor.registry import counter as _counter

__all__ = ["enable", "disable", "is_enabled", "cache_dir", "stats",
           "reset_stats", "ENV_VAR"]

ENV_VAR = "PADDLE_TPU_CACHE_DIR"

_lock = threading.Lock()
_state = {"dir": None, "listening": False}
_counters = {"hits": 0, "misses": 0, "requests": 0}

# registry mirrors of the jax-monitoring-fed counters, so /metrics and
# the per-rank snapshots carry warm-restart evidence too
_m_counters = {
    "hits": _counter("compile_cache_hits_total",
                     "XLA compiles served from the persistent "
                     "compilation cache (disk)"),
    "misses": _counter("compile_cache_misses_total",
                       "XLA compiles that missed the persistent cache "
                       "and compiled for real"),
    "requests": _counter("compile_cache_requests_total",
                         "Compile requests eligible for the persistent "
                         "cache"),
}

# jax monitoring event suffixes -> our counter keys (the full names are
# '/jax/compilation_cache/cache_hits' etc.; matched by suffix so a jax
# upgrade that re-roots the namespace keeps counting)
_EVENT_MAP = {
    "cache_hits": "hits",
    "cache_misses": "misses",
    "compile_requests_use_cache": "requests",
}


def _on_event(event, **kw):
    key = _EVENT_MAP.get(event.rsplit("/", 1)[-1])
    if key is not None:
        with _lock:
            _counters[key] += 1
        _m_counters[key].inc()


def _ensure_listener():
    # idempotent: one listener per process, registered lazily so plain
    # `import paddle_tpu` without a cache dir never touches jax
    # internals
    with _lock:
        if _state["listening"]:
            return
        _state["listening"] = True
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
    except Exception:  # pragma: no cover - jax internals moved
        with _lock:
            _state["listening"] = False


def enable(dirname):
    """Point jax's persistent compilation cache at ``dirname`` (created
    if missing). Thresholds are zeroed so even sub-second test programs
    cache — the warm-restart win scales with compile time, and caching
    a tiny program costs one small file."""
    import jax
    if _mid_process():
        # once per process, not per enable(): retry loops and tests
        # re-point the cache freely and must not spam the log
        from paddle_tpu.core.enforce import warn_once
        warn_once(
            "compile_cache_mid_process",
            "compilation cache enabled mid-process: computations "
            "compiled before enable() were not cached (jax's one-shot "
            "cache state is reset so later compiles are)")
    dirname = os.path.abspath(dirname)
    os.makedirs(dirname, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", dirname)
    # cache everything: the default 1s/0B floors exist to keep prod
    # caches small, but they would silently exclude the small programs
    # the warm-restart tests (and fast iteration loops) rely on
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:  # pragma: no cover - knob renamed upstream
            pass
    _reset_jax_cache_state()
    _ensure_listener()
    with _lock:
        _state["dir"] = dirname
    return dirname


def _mid_process():
    """True when a jax backend already initialized — i.e. something may
    already have compiled, so this enable() is the 'mid-process' path
    whose earlier compiles the cache can never cover."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:  # pragma: no cover - jax internals moved
        return False


def _reset_jax_cache_state():
    # jax initializes its cache object at most ONCE per process, at the
    # first compile — if anything compiled before enable()/disable()
    # flipped the dir, the one-shot init already latched (possibly to
    # "no cache") and the config change would silently do nothing.
    # reset_cache() returns it to pristine so the next compile re-reads
    # the config.
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover - jax internals moved
        pass


def disable():
    import jax
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_cache_state()
    with _lock:
        _state["dir"] = None


def is_enabled():
    return _state["dir"] is not None


def cache_dir():
    return _state["dir"]


def stats():
    """{'hits', 'misses', 'requests'} since process start (or the last
    reset_stats). Hits mean an XLA compile was served from disk —
    a restarted worker with hits > 0 provably skipped recompilation."""
    with _lock:
        return dict(_counters)


def reset_stats():
    with _lock:
        for k in _counters:
            _counters[k] = 0


def enable_from_env():
    """Called from paddle_tpu.core import: activate iff the env asks.
    Returns the cache dir or None."""
    d = os.environ.get(ENV_VAR)
    if d:
        return enable(d)
    return None
