"""Error-checking helpers.

Analog of PADDLE_ENFORCE* / PADDLE_THROW and the stacktrace-carrying
EnforceNotMet exception (ref: paddle/fluid/platform/enforce.h:67,239-354).
Python exceptions already carry tracebacks, so this layer only adds the
uniform exception type and the convenience predicates used throughout the
framework.
"""


class EnforceNotMet(RuntimeError):
    """Raised when a framework invariant is violated."""


class EOFException(Exception):
    """End of a started reader's data (ref: fluid.core.EOFException —
    the non-iterable reader protocol's loop terminator)."""


def enforce(cond, msg="", *fmt_args):
    if not cond:
        raise EnforceNotMet(msg % fmt_args if fmt_args else str(msg))


def enforce_eq(a, b, msg=""):
    if a != b:
        raise EnforceNotMet(f"Expected {a!r} == {b!r}. {msg}")


def enforce_ne(a, b, msg=""):
    if a == b:
        raise EnforceNotMet(f"Expected {a!r} != {b!r}. {msg}")


def enforce_gt(a, b, msg=""):
    if not a > b:
        raise EnforceNotMet(f"Expected {a!r} > {b!r}. {msg}")


def enforce_ge(a, b, msg=""):
    if not a >= b:
        raise EnforceNotMet(f"Expected {a!r} >= {b!r}. {msg}")


def enforce_lt(a, b, msg=""):
    if not a < b:
        raise EnforceNotMet(f"Expected {a!r} < {b!r}. {msg}")


def enforce_le(a, b, msg=""):
    if not a <= b:
        raise EnforceNotMet(f"Expected {a!r} <= {b!r}. {msg}")


def not_none(x, name="value"):
    if x is None:
        raise EnforceNotMet(f"{name} must not be None")
    return x


import threading as _threading

_warned_keys = set()
_warn_lock = _threading.Lock()


def warn_once(key, message, category=UserWarning, stacklevel=3):
    """Emit ``message`` at most once per process per ``key``.

    The dedup is our own set, not the warnings registry, so it survives
    ``warnings.simplefilter("always")`` (pytest and user code both
    flip that): a shim called every step (cuda_profiler, mid-process
    cache enabling) warns exactly once however the filters are set.
    Returns True iff the warning fired."""
    import warnings
    with _warn_lock:
        if key in _warned_keys:
            return False
        _warned_keys.add(key)
    warnings.warn(message, category, stacklevel=stacklevel)
    return True


def _reset_warn_once(key=None):
    """TESTS ONLY: forget that ``key`` (or, with None, every key) has
    warned, so a ``pytest.warns`` assertion no longer depends on being
    the process's first caller of the shim under test (the ordering
    flake CHANGES.md PR 3 noted). Production code must not call this —
    once-per-process is the contract."""
    with _warn_lock:
        if key is None:
            _warned_keys.clear()
        else:
            _warned_keys.discard(key)


warn_once.reset_for_tests = _reset_warn_once
