"""Global RNG state.

The reference's random ops are stateful (per-device curand generators,
seeded by op attr or globally). JAX RNG is functional; this module bridges
the two: a process-global seed + draw counter that mints fresh
`jax.random` keys for eager calls, while jitted/static paths thread keys
explicitly.
"""

import threading

import jax

_state = threading.local()
_GLOBAL = {"seed": 0, "counter": 0}
_lock = threading.Lock()


def seed(s):
    """paddle.seed parity: reset the global generator."""
    with _lock:
        _GLOBAL["seed"] = int(s)
        _GLOBAL["counter"] = 0


def next_key():
    """Mint a fresh PRNG key (eager use only — impure)."""
    with _lock:
        k = jax.random.fold_in(jax.random.PRNGKey(_GLOBAL["seed"]),
                               _GLOBAL["counter"])
        _GLOBAL["counter"] += 1
    return k


def key_for(op_seed):
    """Deterministic key for ops that carry their own seed attr (the
    reference pattern: seed=0 means 'use global')."""
    if op_seed:
        return jax.random.PRNGKey(int(op_seed))
    return next_key()
