"""paddle_tpu — a TPU-native deep learning framework.

A from-scratch rebuild of the capabilities of PaddlePaddle Fluid
(reference: /root/reference, Fluid 1.5 era) designed idiomatically for TPU:

- compute lowers to XLA through JAX; one compiled computation per training
  step instead of the reference's per-op interpreter loop
  (ref: paddle/fluid/framework/executor.cc:417 hot loop),
- SPMD parallelism over `jax.sharding.Mesh` with XLA collectives replacing
  ParallelExecutor + NCCL (ref: paddle/fluid/framework/parallel_executor.cc),
- ragged sequences via dense padding + segment metadata replacing LoD
  (ref: paddle/fluid/framework/lod_tensor.h),
- Pallas kernels for hot ops; a native C++ host data pipeline.

Public surface mirrors the reference's `paddle.fluid` so users can migrate:
``paddle_tpu.layers``, ``paddle_tpu.optimizer``, ``paddle_tpu.static``
(Program/Executor), eager by default (the reference's dygraph).
"""

from paddle_tpu.core import dtypes
from paddle_tpu.core.dtypes import (
    float32, float64, float16, bfloat16, int8, int16, int32, int64, bool_,
    uint8,
)
from paddle_tpu.core.enforce import EnforceNotMet, enforce, enforce_eq
from paddle_tpu.core.flags import flags, get_flag, set_flags
from paddle_tpu.core.place import (
    CPUPlace, TPUPlace, Place, default_place, is_compiled_with_tpu,
    is_compiled_with_cuda, device_count, set_device, get_device,
    cpu_places, cuda_places, cuda_pinned_places, tpu_places,
    CUDAPlace, CUDAPinnedPlace,
)

from paddle_tpu import ops
from paddle_tpu import install_check
from paddle_tpu import transpiler
from paddle_tpu import layers
from paddle_tpu import nn
from paddle_tpu import initializer
from paddle_tpu import optimizer
from paddle_tpu import regularizer
from paddle_tpu import clip
from paddle_tpu import metrics
from paddle_tpu import static
from paddle_tpu.static import (
    Program, program_guard, default_main_program, default_startup_program,
    Executor, data, enable_static, disable_static,
)
from paddle_tpu import io
from paddle_tpu import amp
from paddle_tpu import parallel
from paddle_tpu import distributed
from paddle_tpu import dataio
from paddle_tpu import reader
from paddle_tpu import profiler
from paddle_tpu.framework import (
    ParamAttr, Variable, to_variable, no_grad, grad,
)
from paddle_tpu import backward
from paddle_tpu import nets
from paddle_tpu import dygraph
from paddle_tpu import incubate
from paddle_tpu import compiler
from paddle_tpu.compiler import (
    CompiledProgram, ExecutionStrategy, BuildStrategy,
)
in_dygraph_mode = dygraph.enabled   # fluid.in_dygraph_mode parity
from paddle_tpu.dataio.feeder import DataFeeder
# the two most common top-level paddle.* calls in fluid scripts:
# paddle.batch(reader, bs) and paddle.dataset.mnist.train().
# io.batch keeps paddle.batch's drop_last=False default (the raw
# batch_reader helper defaults True, which would silently drop the
# final partial batch of a migrated eval loop)
from paddle_tpu.io import batch
from paddle_tpu.dataio import dataset
from paddle_tpu.framework import WeightNormParamAttr
from paddle_tpu import lod_tensor
from paddle_tpu.lod_tensor import (
    create_lod_tensor, create_random_int_lodtensor,
)
from paddle_tpu import recordio_writer
from paddle_tpu import distributions
from paddle_tpu import contrib
from paddle_tpu import inference

from paddle_tpu.version import __version__  # noqa: E402
