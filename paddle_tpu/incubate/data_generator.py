"""Dataset-file data generators.

Parity: python/paddle/fluid/incubate/data_generator/__init__.py
(DataGenerator:21, MultiSlotDataGenerator:282). Users subclass,
override ``generate_sample(line)`` (and optionally
``generate_batch``), and the runner emits MultiSlot text lines —
``<n> v1 ... vn`` per slot — the exact format the native MultiSlot
parser reads (native/src/strings.cc, dataio/fluid_dataset.py), so
generated files feed train_from_dataset directly.
"""

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int):
            raise ValueError(f"line_limit {type(line_limit)} must be int")
        if line_limit < 1:
            raise ValueError("line_limit can not be less than 1")
        self._line_limit = line_limit

    # -- user hooks --------------------------------------------------------
    def generate_sample(self, line):
        """Override: return a zero-arg iterator of parsed samples
        ([(slot_name, [values...]), ...]) for one input line."""
        raise NotImplementedError(
            "subclasses must implement generate_sample(line)")

    def generate_batch(self, samples):
        """Optional override: batch-level processing; default yields
        the samples unchanged."""
        def local_iter():
            yield from samples
        return local_iter

    # -- runners -----------------------------------------------------------
    def _flush_batch(self, batch_samples, out):
        for sample in self.generate_batch(batch_samples)():
            out.write(self._gen_str(sample))

    def run_from_memory(self, out=None):
        """Emit samples produced by generate_sample(None) (debug /
        benchmarking path)."""
        out = out or sys.stdout
        batch = []
        for sample in self.generate_sample(None)():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                self._flush_batch(batch, out)
                batch = []
        if batch:
            self._flush_batch(batch, out)

    def run_from_stdin(self, inp=None, out=None):
        """Parse each input line with generate_sample and write
        MultiSlot text to stdout (the dataset-preprocessing pipeline
        contract: hadoop/shell pipes run this script per shard)."""
        inp = inp or sys.stdin
        out = out or sys.stdout
        batch = []
        for n, line in enumerate(inp, 1):
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush_batch(batch, out)
                    batch = []
            if self._line_limit and n >= self._line_limit:
                break
        if batch:
            self._flush_batch(batch, out)

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator (or override _gen_str)")


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """[(name, [v...]), ...] -> "n v1 .. vn m w1 .. wm\\n" and track
        per-slot dtype in _proto_info (uint64 until a float appears)."""
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of generate_sample must be list/tuple of "
                "(name, [values...]) pairs")
        # validate fully into a local proto, THEN commit — a rejected
        # line must not leave half-updated slot state behind
        first = self._proto_info is None
        proto = [] if first else list(self._proto_info)
        if not first and len(line) != len(proto):
            raise ValueError(
                "the field set of two lines are inconsistent: "
                f"{len(line)} vs {len(proto)}")
        parts = []
        for idx, (name, elements) in enumerate(line):
            if not isinstance(name, str):
                raise ValueError(f"slot name {type(name)} must be str")
            if not isinstance(elements, list) or not elements:
                raise ValueError(
                    f"slot '{name}': elements must be a non-empty list "
                    "(pad in generate_sample if needed)")
            if first:
                proto.append((name, "uint64"))
            elif name != proto[idx][0]:
                raise ValueError(
                    f"field name mismatch: require "
                    f"<{proto[idx][0]}>, got <{name}>")
            parts.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, bool):
                    # bool IS an int subclass but str(True) would write
                    # the literal 'True' into the MultiSlot file
                    raise ValueError(
                        f"slot '{name}': bool elements are not valid "
                        "MultiSlot values — cast to int")
                if isinstance(elem, float):
                    proto[idx] = (name, "float")
                elif not isinstance(elem, int):
                    raise ValueError(
                        f"slot '{name}': element type {type(elem)} must "
                        "be int or float")
                parts.append(str(elem))
        self._proto_info = proto
        return " ".join(parts) + "\n"
