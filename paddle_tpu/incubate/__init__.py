"""incubate namespace.

Parity: python/paddle/fluid/incubate/ — fleet (re-exported from
paddle_tpu.distributed) and data_generator (MultiSlot dataset-file
writers).
"""

from paddle_tpu.incubate import data_generator      # noqa: F401
from paddle_tpu.distributed import fleet            # noqa: F401

__all__ = ["data_generator", "fleet"]
