"""fluid.backward parity — re-exports the static autodiff entry points."""

from paddle_tpu.static.backward import (
    append_backward, gradients, calc_gradient, GRAD_SUFFIX,
)

__all__ = ["append_backward", "gradients", "calc_gradient",
           "GRAD_SUFFIX"]
