"""The deprecated high-level Trainer/Inferencer API (contrib surface).

Parity: contrib/trainer.py (Trainer: program-building train loop with
event handlers + checkpointing) and contrib/inferencer.py (Inferencer:
load params + run). Deprecated in the reference too — kept thin here:
both are facades over the static Program/Executor/io machinery.
"""

import os

import numpy as np

__all__ = ["Trainer", "Inferencer", "BeginEpochEvent", "EndEpochEvent",
           "BeginStepEvent", "EndStepEvent"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch, self.step = epoch_id, step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch, self.step, self.metrics = epoch_id, step_id, metrics


class Trainer:
    """train_func() builds the loss (and optionally returns [loss, ...]
    metric vars) inside a fresh program; optimizer_func() returns the
    optimizer. ``train(...)`` drives epochs of a reader with event
    handlers — the reference's event protocol (Begin/EndEpoch,
    Begin/EndStep)."""

    def __init__(self, train_func, optimizer_func, place=None,
                 param_path=None, parallel=False):
        import paddle_tpu as pt
        self._pt = pt
        self.main = pt.Program()
        self.startup = pt.Program()
        # fresh name scope: the Inferencer rebuilds the net later and
        # must produce the SAME parameter names to load the checkpoint
        with pt.framework.unique_name.guard(), \
                pt.static.program_guard(self.main, self.startup):
            out = train_func()
            outs = out if isinstance(out, (list, tuple)) else [out]
            self.loss = outs[0]
            self.metrics = list(outs)
            opt = optimizer_func()
            opt.minimize(self.loss)
        self.place = place if place is not None else pt.CPUPlace()
        self.exe = pt.static.Executor(self.place)
        self.scope = pt.static.Scope()
        with pt.static.scope_guard(self.scope):
            self.exe.run(self.startup)
        if param_path and os.path.isdir(param_path):
            with pt.static.scope_guard(self.scope):
                pt.io.load_params(self.exe, param_path,
                                  main_program=self.main)

    def train(self, num_epochs, event_handler, reader, feed_order):
        pt = self._pt
        fetch = [m.name for m in self.metrics]
        with pt.static.scope_guard(self.scope):
            for epoch in range(num_epochs):
                event_handler(BeginEpochEvent(epoch))
                for step, data in enumerate(reader()):
                    event_handler(BeginStepEvent(epoch, step))
                    feed = {n: np.asarray([row[i] for row in data])
                            for i, n in enumerate(feed_order)}
                    metrics = self.exe.run(self.main, feed=feed,
                                           fetch_list=fetch)
                    event_handler(EndStepEvent(epoch, step, metrics))
                event_handler(EndEpochEvent(epoch))

    def save_params(self, param_path):
        pt = self._pt
        with pt.static.scope_guard(self.scope):
            pt.io.save_params(self.exe, param_path,
                              main_program=self.main)

    def stop(self):
        pass


class Inferencer:
    """infer_func() builds the inference graph in a fresh program;
    params load from param_path; ``infer(feed)`` runs it."""

    def __init__(self, infer_func, param_path, place=None):
        import paddle_tpu as pt
        self._pt = pt
        self.main = pt.Program()
        startup = pt.Program()
        with pt.framework.unique_name.guard(), \
                pt.static.program_guard(self.main, startup):
            out = infer_func()
            outs = out if isinstance(out, (list, tuple)) else [out]
            self.fetch = [o.name for o in outs]
        self.place = place if place is not None else pt.CPUPlace()
        self.exe = pt.static.Executor(self.place)
        self.scope = pt.static.Scope()
        with pt.static.scope_guard(self.scope):
            self.exe.run(startup)
            pt.io.load_params(self.exe, param_path,
                              main_program=self.main)

    def infer(self, inputs):
        pt = self._pt
        with pt.static.scope_guard(self.scope):
            return self.exe.run(self.main, feed=inputs,
                                fetch_list=self.fetch)
