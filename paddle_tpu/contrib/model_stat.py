"""Model statistics: parameter and FLOP summary for a static Program.

Parity: python/paddle/fluid/contrib/model_stat.py (summary: per-layer
table of output shape / param count / FLOPs, plus totals).
"""

import numpy as np

__all__ = ["summary"]

_MUL_OPS = {"mul", "matmul", "fc"}
_CONV_OPS = {"conv2d", "conv2d_fusion", "depthwise_conv2d"}


def _numel(shape):
    return int(np.prod([d if d and d > 0 else 1 for d in shape]))


def summary(main_program, print_fn=print):
    """Prints the per-op table and returns (total_params, total_flops).
    FLOPs counted for matmul/conv ops (2*macs) like the reference;
    elementwise ops are counted by output size."""
    total_params = 0
    total_flops = 0
    rows = []
    gb = main_program.global_block()
    param_names = {v.name for v in gb.vars.values()
                   if getattr(v, "persistable", False)}
    for block in main_program.blocks:
        for op in block.ops:
            p = 0
            for name in op.input_names():
                v = gb.vars.get(name)
                if v is not None and name in param_names:
                    p += _numel(v.shape)
            out_shape = None
            f = 0
            outs = op.output_names()
            if outs:
                ov = block.vars.get(outs[0]) or gb.vars.get(outs[0])
                if ov is not None and getattr(ov, "shape", None):
                    out_shape = tuple(ov.shape)
                    if op.type in _CONV_OPS and len(out_shape) >= 3:
                        # macs per output element = weight size / C_out;
                        # every [N, C_out, H, W] element costs that many
                        c_out = max(out_shape[1], 1)
                        f = 2 * _numel(out_shape) * max(p // c_out, 1)
                    elif op.type in _MUL_OPS or op.type in _CONV_OPS:
                        f = 2 * p * _numel(out_shape[:1])
                    else:
                        f = _numel(out_shape)
            total_params += p
            total_flops += f
            rows.append((op.type, out_shape, p, f))
    width = max((len(r[0]) for r in rows), default=4) + 2
    print_fn(f"{'op':<{width}}{'output':<20}{'params':>12}{'flops':>14}")
    for t, s, p, f in rows:
        print_fn(f"{t:<{width}}{str(s):<20}{p:>12}{f:>14}")
    print_fn(f"Total params: {total_params:,}  "
             f"Total FLOPs (approx): {total_flops:,}")
    return total_params, total_flops
