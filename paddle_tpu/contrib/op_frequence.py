"""Op-frequency statistics over a static Program.

Parity: python/paddle/fluid/contrib/op_frequence.py (op_freq_statistic:
single-op counts plus adjacent-op-pair counts over all blocks).
"""

from collections import OrderedDict

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (single_op_count, pair_op_count), both OrderedDicts
    sorted by descending frequency. Pairs are adjacent (prev, next) op
    types within a block, keyed "a,b" like the reference."""
    uni = {}
    pair = {}
    for block in program.blocks:
        prev = None
        for op in block.ops:
            t = op.type
            uni[t] = uni.get(t, 0) + 1
            if prev is not None:
                k = f"{prev},{t}"
                pair[k] = pair.get(k, 0) + 1
            prev = t
    order = lambda d: OrderedDict(
        sorted(d.items(), key=lambda kv: (-kv[1], kv[0])))
    return order(uni), order(pair)
