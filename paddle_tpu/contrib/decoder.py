"""Beam-search decoder helper (contrib surface).

Parity: contrib/decoder/beam_search_decoder.py (BeamSearchDecoder over a
state cell). The reference builds a dynamic while-op graph; here the
decode loop is a jittable Python/`lax`-friendly loop over a step
function, using ops.misc.beam_search for the per-step top-k and
ops.aliases.beam_search_decode for the final backtrack — the same
TPU-native machinery models/transformer.py uses for NMT decoding.
"""

import jax.numpy as jnp

from paddle_tpu.ops.aliases import beam_search_decode
from paddle_tpu.ops.misc import beam_search

__all__ = ["BeamSearchDecoder"]


class BeamSearchDecoder:
    """decode(init_state, bos_id) runs ``max_len`` steps of
    ``step_fn(state, last_ids) -> (log_probs [B*beam, V], new_state)``
    with beam pruning each step, then backtracks the best sequences.

    step_fn's state must be a pytree whose leaves have leading dim
    B*beam (rows are re-gathered by parent after every pruning step).
    """

    def __init__(self, step_fn, beam_size=4, end_token=1,
                 max_len=32, length_penalty=0.0):
        self.step_fn = step_fn
        self.beam_size = beam_size
        self.end_token = end_token
        self.max_len = max_len
        self.length_penalty = length_penalty

    def decode(self, init_state, bos_id, batch_size):
        import jax
        bb = batch_size * self.beam_size
        ids = jnp.full((bb, 1), bos_id, jnp.int32)
        # only slot 0 of each beam group is live at t=0
        scores = jnp.where(jnp.arange(bb) % self.beam_size == 0,
                           0.0, -1e9).astype(jnp.float32)
        state = init_state
        step_ids, step_parents = [], []
        for t in range(self.max_len):
            log_probs, state = self.step_fn(state, ids[:, -1])
            ids, scores, parent = beam_search(
                log_probs, scores, ids, self.beam_size,
                end_token=self.end_token,
                length_penalty=self.length_penalty, step=t + 1)
            state = jax.tree.map(lambda s: s[parent], state)
            step_ids.append(ids[:, -1])
            step_parents.append(parent)
        seqs = beam_search_decode(jnp.stack(step_ids),
                                  jnp.stack(step_parents),
                                  end_token=self.end_token)
        return seqs, scores


class InitState:
    """contrib/decoder/beam_search_decoder.py InitState parity: the
    initial value of one decoder hidden state. ``init=`` uses the
    tensor directly; ``init_boot=`` + shape/value builds a value-filled
    state batch-sized like init_boot (the reference's
    fill_constant_batch_size_like form)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self.value = init
        elif init_boot is not None:
            boot = jnp.asarray(init_boot)
            tail = tuple(int(s) for s in (shape or boot.shape[1:])
                         if s not in (-1, None))
            self.value = jnp.full((boot.shape[0],) + tail, value,
                                  boot.dtype if dtype is None else dtype)
        else:
            raise ValueError("InitState needs init= (or init_boot=)")
        self.need_reorder = need_reorder


class StateCell:
    """StateCell parity: named states + named step inputs + a
    registered @state_cell.state_updater callable that maps
    (inputs, states) -> new states. The updater is a real callable, so
    it replays under lax.scan — the same adaptation this framework uses
    for While/StaticRNN (a traced with-block cannot be re-executed)."""

    def __init__(self, inputs, states, out_state, name=None):
        self._input_names = list(inputs)
        self._init_states = {k: v for k, v in states.items()}
        self.out_state = out_state
        self._updater = None
        self._cur_inputs = {}
        self._cur_states = {}
        self._new_states = {}

    def state_updater(self, fn):
        self._updater = fn
        return fn

    # -- accessors used inside the updater --------------------------------
    def get_input(self, name):
        return self._cur_inputs[name]

    def get_state(self, name):
        return self._cur_states[name]

    def set_state(self, name, value):
        self._new_states[name] = value

    def compute_state(self, inputs):
        """Run the registered updater on this step's inputs."""
        if self._updater is None:
            raise ValueError("no @state_cell.state_updater registered")
        self._cur_inputs = dict(inputs)
        self._new_states = {}
        self._updater(self)

    def update_states(self):
        self._cur_states = {**self._cur_states, **self._new_states}
        self._new_states = {}

    def initial_states(self):
        return {k: jnp.asarray(v.value) for k, v in
                self._init_states.items()}

    def out_value(self):
        return self._cur_states[self.out_state]


class TrainingDecoder:
    """TrainingDecoder parity in this framework's callable-block form
    (the reference's `with decoder.block():` builds a sub-block an op
    replays; under tracing the body must be a callable — the documented
    StaticRNN adaptation, layers/control_flow_classes.py):

        decoder = TrainingDecoder(state_cell)
        decoder.step_input(trg_embedding)          # [B, T, D]
        @decoder.block
        def _(decoder, current_word):
            decoder.state_cell.compute_state(inputs={'x': current_word})
            score = layers.fc(...)                 # any per-step layers
            decoder.state_cell.update_states()
            decoder.output(score)
        out = decoder()                            # [B, T, V]
    """

    def __init__(self, state_cell, name=None):
        self.state_cell = state_cell
        self._seqs = []
        self._block = None
        self._step_outputs = None

    def step_input(self, seq):
        self._seqs.append(jnp.asarray(seq))
        return seq

    def block(self, fn):
        """Register the per-step body (decorator)."""
        self._block = fn
        return fn

    def output(self, *outs):
        self._step_outputs = outs

    def __call__(self):
        import jax
        if self._block is None or not self._seqs:
            raise ValueError("TrainingDecoder needs step_input() and a "
                             "@decoder.block body")
        xs = tuple(jnp.moveaxis(s, 1, 0) for s in self._seqs)  # T-major

        def body(states, xts):
            self.state_cell._cur_states = dict(states)
            self._step_outputs = None
            self._block(self, *xts)
            outs = self._step_outputs or (self.state_cell.out_value(),)
            return dict(self.state_cell._cur_states), tuple(outs)

        init = self.state_cell.initial_states()
        # dry step OUTSIDE the scan so module parameters are created in
        # the enclosing frame (creating them inside the scan body would
        # leak its tracers into the param store); its state/output
        # changes are discarded
        body(dict(init), tuple(x[0] for x in xs))
        _, outs = jax.lax.scan(body, init, xs)
        outs = [jnp.moveaxis(o, 0, 1) for o in outs]
        return outs[0] if len(outs) == 1 else outs


__all__ += ["InitState", "StateCell", "TrainingDecoder"]
