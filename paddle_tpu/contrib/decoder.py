"""Beam-search decoder helper (contrib surface).

Parity: contrib/decoder/beam_search_decoder.py (BeamSearchDecoder over a
state cell). The reference builds a dynamic while-op graph; here the
decode loop is a jittable Python/`lax`-friendly loop over a step
function, using ops.misc.beam_search for the per-step top-k and
ops.aliases.beam_search_decode for the final backtrack — the same
TPU-native machinery models/transformer.py uses for NMT decoding.
"""

import jax.numpy as jnp

from paddle_tpu.ops.aliases import beam_search_decode
from paddle_tpu.ops.misc import beam_search

__all__ = ["BeamSearchDecoder"]


class BeamSearchDecoder:
    """decode(init_state, bos_id) runs ``max_len`` steps of
    ``step_fn(state, last_ids) -> (log_probs [B*beam, V], new_state)``
    with beam pruning each step, then backtracks the best sequences.

    step_fn's state must be a pytree whose leaves have leading dim
    B*beam (rows are re-gathered by parent after every pruning step).
    """

    def __init__(self, step_fn, beam_size=4, end_token=1,
                 max_len=32, length_penalty=0.0):
        self.step_fn = step_fn
        self.beam_size = beam_size
        self.end_token = end_token
        self.max_len = max_len
        self.length_penalty = length_penalty

    def decode(self, init_state, bos_id, batch_size):
        import jax
        bb = batch_size * self.beam_size
        ids = jnp.full((bb, 1), bos_id, jnp.int32)
        # only slot 0 of each beam group is live at t=0
        scores = jnp.where(jnp.arange(bb) % self.beam_size == 0,
                           0.0, -1e9).astype(jnp.float32)
        state = init_state
        step_ids, step_parents = [], []
        for t in range(self.max_len):
            log_probs, state = self.step_fn(state, ids[:, -1])
            ids, scores, parent = beam_search(
                log_probs, scores, ids, self.beam_size,
                end_token=self.end_token,
                length_penalty=self.length_penalty, step=t + 1)
            state = jax.tree.map(lambda s: s[parent], state)
            step_ids.append(ids[:, -1])
            step_parents.append(parent)
        seqs = beam_search_decode(jnp.stack(step_ids),
                                  jnp.stack(step_parents),
                                  end_token=self.end_token)
        return seqs, scores
