"""Contrib layers: fused elementwise+activation and the basic RNN API.

Parity: contrib/layers/nn.py (fused_elemwise_activation) and
contrib/layers/rnn_impl.py (BasicGRUUnit, basic_gru, BasicLSTMUnit,
basic_lstm — multi-layer, optionally bidirectional RNN stacks).
TPU-native: the "fusion" is XLA's job; the stacks compose ops.rnn's
scan-based lstm/gru (one big input projection per layer on the MXU).
"""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.ops import rnn as _rnn

__all__ = ["fused_elemwise_activation", "basic_gru", "basic_lstm",
           "BasicGRUUnit", "BasicLSTMUnit"]

_BINARY = {
    "elementwise_add": lambda a, b: a + b,
    "elementwise_sub": lambda a, b: a - b,
    "elementwise_mul": lambda a, b: a * b,
}
_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "scale": lambda x, scale=1.0: x * scale,
    "identity": lambda x: x,
}


def fused_elemwise_activation(x, y, functor_list, axis=-1,
                              save_intermediate_out=False):
    """contrib/layers/nn.py fused_elemwise_activation. Reference functor
    composition (its docstring + test_fused_elemwise_activation_op.py):
    binary-first ['elementwise_add', 'relu'] → x + relu(y)
    (out = Binary(x, Unary(y)), intermediate = Unary(y)); unary-first
    ['relu', 'elementwise_add'] → relu(x + y)
    (out = Unary(Binary(x, y)), intermediate = Binary(x, y)). On TPU the
    fusion itself is XLA's job — this is the same graph either way."""
    a, b = functor_list
    if a in _BINARY:
        inter = _ACTS[b](y)
        out = _BINARY[a](x, inter)
    else:
        inter = _BINARY[b](x, y)
        out = _ACTS[a](inter)
    if save_intermediate_out:
        return out, inter
    return out


def _init(rng, shape, scale=0.1):
    return (scale * jax.random.normal(rng, shape)).astype(jnp.float32)


class BasicLSTMUnit:
    """One LSTM cell step (rnn_impl.py BasicLSTMUnit): call(h, c, x) ->
    (h', c'). Gate order i, f (with forget_bias), c, o."""

    def __init__(self, hidden_size, input_size, forget_bias=1.0, rng=None,
                 w=None, b=None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(rng)
        self.w = w if w is not None else _init(
            k1, (input_size + hidden_size, 4 * hidden_size))
        self.b = b if b is not None else jnp.zeros(
            (4 * hidden_size,), jnp.float32)
        self.forget_bias = forget_bias

    def __call__(self, x, h, c):
        gates = jnp.concatenate([x, h], -1) @ self.w + self.b
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c2 = (jax.nn.sigmoid(f + self.forget_bias) * c
              + jax.nn.sigmoid(i) * jnp.tanh(g))
        h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
        return h2, c2


class BasicGRUUnit:
    """One GRU cell step (rnn_impl.py BasicGRUUnit): call(x, h) -> h'."""

    def __init__(self, hidden_size, input_size, rng=None, w_ih=None,
                 w_hh=None, b=None):
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(rng)
        self.w_ih = w_ih if w_ih is not None else _init(
            k1, (input_size, 3 * hidden_size))
        self.w_hh = w_hh if w_hh is not None else _init(
            k2, (hidden_size, 3 * hidden_size))
        self.b = b if b is not None else jnp.zeros(
            (3 * hidden_size,), jnp.float32)

    def __call__(self, x, h):
        out, _ = _rnn.gru(x[:, None, :], self.w_ih, self.w_hh, b=self.b,
                          h0=h)
        return out[:, 0]


def _stack(cell_fn, input, num_layers, bidirectional, lengths):
    """Run a layer stack, concatenating directions per layer. cell_fn
    returns (outputs, state); states come back grouped per layer —
    (fwd, bwd) tuples when bidirectional — for every state the cell
    carries (h for GRU, (h, c) zipped apart by the caller for LSTM)."""
    x = input
    states = []
    for layer in range(num_layers):
        fwd, sf = cell_fn(x, layer, False, lengths)
        if bidirectional:
            bwd, sb = cell_fn(x, layer, True, lengths)
            x = jnp.concatenate([fwd, bwd], -1)
            states.append((sf, sb))
        else:
            x = fwd
            states.append(sf)
    return x, states


def _init_state(init, layer, reverse, dirs):
    """Pick the (layer, direction) slice of an initial-state argument:
    None, a [L*dirs, B, H] array, or a list indexed layer-major
    (fwd[, bwd] per layer) — the rnn_impl.py layout."""
    if init is None:
        return None
    idx = layer * dirs + (1 if reverse else 0)
    if isinstance(init, (list, tuple)):
        return init[idx]
    return init[idx] if init.ndim == 3 else init


def basic_lstm(input, init_hidden=None, init_cell=None, hidden_size=128,
               num_layers=1, sequence_length=None, bidirectional=False,
               forget_bias=1.0, seed=0, params=None):
    """rnn_impl.py basic_lstm: stacked (optionally bidirectional) LSTM.
    input [B, T, D]; init_hidden/init_cell: per-(layer, direction)
    initial states ([L*dirs, B, H] array or list). Returns
    (output [B, T, H*(2 if bidir)], last_hidden list, last_cell list).

    With ``params=None`` the weights are FROZEN seed-derived constants —
    a fixed-weight shim, not trainable (the reference's rnn_impl stacks
    create trainable parameters). To train, pass ``params``: a
    layer-major list (fwd[, bwd] per layer, index = layer*dirs + dir) of
    dicts with "w_ih" [D, 4H], "w_hh" [H, 4H] and optional "b" [4H]
    (forget_bias is still added to the f-gate slice on top of "b", as
    BasicLSTMUnit does); gradients flow through them."""
    rng = jax.random.PRNGKey(seed)
    keys = jax.random.split(rng, num_layers * 2 + 1)
    dirs = 2 if bidirectional else 1

    def cell(x, layer, reverse, lengths):
        d = x.shape[-1]
        if params is not None:
            p = params[layer * dirs + (1 if reverse else 0)]
            w_ih, w_hh = p["w_ih"], p["w_hh"]
            b = p.get("b")
            b = jnp.zeros((4 * hidden_size,), jnp.float32) \
                if b is None else jnp.asarray(b)
        else:
            k = keys[layer * 2 + (1 if reverse else 0)]
            k1, k2 = jax.random.split(k)
            w_ih = _init(k1, (d, 4 * hidden_size))
            w_hh = _init(k2, (hidden_size, 4 * hidden_size))
            b = jnp.zeros((4 * hidden_size,), jnp.float32)
        b = b.at[hidden_size:2 * hidden_size].add(forget_bias)
        out, (h, c) = _rnn.lstm(x, w_ih, w_hh, b=b,
                                h0=_init_state(init_hidden, layer,
                                               reverse, dirs),
                                c0=_init_state(init_cell, layer,
                                               reverse, dirs),
                                lengths=lengths, reverse=reverse)
        return out, (h, c)

    out, states = _stack(cell, input, num_layers, bidirectional,
                         sequence_length)
    # split the per-layer (h, c) states into matching h / c lists,
    # keeping the (fwd, bwd) grouping when bidirectional
    if bidirectional:
        last_h = [(sf[0], sb[0]) for sf, sb in states]
        last_c = [(sf[1], sb[1]) for sf, sb in states]
    else:
        last_h = [s[0] for s in states]
        last_c = [s[1] for s in states]
    return out, last_h, last_c


def basic_gru(input, init_hidden=None, hidden_size=128, num_layers=1,
              sequence_length=None, bidirectional=False, seed=0,
              params=None):
    """rnn_impl.py basic_gru: stacked (optionally bidirectional) GRU.
    Returns (output, last_hidden list).

    With ``params=None`` the weights are FROZEN seed-derived constants
    (fixed-weight shim, untrainable); pass ``params`` — a layer-major
    list (fwd[, bwd] per layer) of dicts with "w_ih" [D, 3H], "w_hh"
    [H, 3H] and optional "b" [3H] — to train them."""
    rng = jax.random.PRNGKey(seed)
    keys = jax.random.split(rng, num_layers * 2 + 1)
    dirs = 2 if bidirectional else 1

    def cell(x, layer, reverse, lengths):
        d = x.shape[-1]
        if params is not None:
            p = params[layer * dirs + (1 if reverse else 0)]
            out, h = _rnn.gru(x, p["w_ih"], p["w_hh"], b=p.get("b"),
                              h0=_init_state(init_hidden, layer, reverse,
                                             dirs),
                              lengths=lengths, reverse=reverse)
            return out, h
        k = keys[layer * 2 + (1 if reverse else 0)]
        k1, k2 = jax.random.split(k)
        w_ih = _init(k1, (d, 3 * hidden_size))
        w_hh = _init(k2, (hidden_size, 3 * hidden_size))
        out, h = _rnn.gru(x, w_ih, w_hh,
                          h0=_init_state(init_hidden, layer, reverse,
                                         dirs),
                          lengths=lengths, reverse=reverse)
        return out, h

    out, last_h = _stack(cell, input, num_layers, bidirectional,
                         sequence_length)
    return out, last_h
