"""Contrib utils: HDFS shell client + lookup-table checkpoint helpers.

Parity: contrib/utils/hdfs_utils.py (HDFSClient — popen wrappers over the
``hadoop fs`` CLI, the same shell-pipeline approach as the reference's
io/fs.cc) and contrib/utils/lookup_table_utils.py (moving distributed
lookup-table checkpoints between pserver shard layout and inference
form — here: SparseEmbeddingTable checkpoints ↔ dense numpy arrays).
"""

import os
import subprocess

import numpy as np

__all__ = ["HDFSClient", "sparse_table_to_dense",
           "dense_to_sparse_table"]


class HDFSClient:
    """Thin ``hadoop fs`` wrapper (hdfs_utils.py HDFSClient). Commands
    shell out like the reference (io/shell popen pipelines); raises
    RuntimeError with stderr if the binary is missing/fails. The
    ``hadoop_bin`` is injectable for tests."""

    def __init__(self, hadoop_home=None, configs=None, hadoop_bin=None):
        self.hadoop_bin = hadoop_bin or (
            os.path.join(hadoop_home, "bin", "hadoop")
            if hadoop_home else "hadoop")
        self.configs = configs or {}

    class BinaryMissing(RuntimeError):
        pass

    def _run(self, *args):
        cmd = [self.hadoop_bin, "fs"]
        for k, v in self.configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            r = subprocess.run(cmd, capture_output=True, text=True)
        except FileNotFoundError as e:
            raise self.BinaryMissing(
                f"hadoop binary not found: {self.hadoop_bin}") from e
        if r.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed: {r.stderr[-500:]}")
        return r.stdout

    def ls(self, path):
        out = self._run("-ls", path)
        return [ln.split()[-1] for ln in out.splitlines()
                if ln and not ln.startswith("Found")]

    def is_exist(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except self.BinaryMissing:
            raise      # a config error must not read as "path absent"
        except RuntimeError:
            return False

    def upload(self, hdfs_path, local_path, overwrite=False):
        args = ["-put"] + (["-f"] if overwrite else []) \
            + [local_path, hdfs_path]
        self._run(*args)

    def download(self, hdfs_path, local_path):
        self._run("-get", hdfs_path, local_path)

    def delete(self, path):
        self._run("-rm", "-r", path)

    def makedirs(self, path):
        self._run("-mkdir", "-p", path)


def sparse_table_to_dense(dirname, name, vocab_size):
    """lookup_table_utils parity (convert sparse checkpoint for
    inference): load a SparseEmbeddingTable checkpoint and materialize
    the dense [vocab_size, dim] matrix. Ids the table never trained
    stay ZERO rows — an inference table only serves trained ids."""
    from paddle_tpu.distributed.sparse_embedding import (
        SparseEmbeddingTable)
    t = SparseEmbeddingTable(1)
    t.load(dirname, name)
    # (0, dim) empty rows still carry the true dim in shape[1]
    dim = t.shards[0].rows.shape[1]
    dense = np.zeros((vocab_size, dim), np.float32)
    for sh in t.shards:
        ids, rows, _slot = sh.state()
        keep = ids < vocab_size
        dense[ids[keep]] = rows[keep]
    return dense


def dense_to_sparse_table(dense, dirname, name, num_shards=1):
    """Inverse: seed a sparse table checkpoint from a dense matrix
    (e.g. converting a single-host embedding into the PS layout)."""
    from paddle_tpu.distributed.sparse_embedding import (
        SparseEmbeddingTable)
    dense = np.asarray(dense, np.float32)
    t = SparseEmbeddingTable(dense.shape[1], num_shards=num_shards)
    ids = np.arange(dense.shape[0], dtype=np.int64)
    from paddle_tpu.distributed.sparse_embedding import _hash_ids
    sh = _hash_ids(ids, num_shards)
    for s in range(num_shards):
        m = sh == s
        t.shards[s].load(ids[m], dense[m],
                         np.zeros_like(dense[m]))
    t.save(dirname, name)
    return t
