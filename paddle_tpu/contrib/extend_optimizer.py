"""Decoupled weight decay for any optimizer class.

Parity: contrib/extend_optimizer/extend_optimizer_with_weight_decay.py
(extend_with_decoupled_weight_decay: wraps a base optimizer so the decay
is applied to the PARAMETER directly, not folded into the gradient —
AdamW-style decoupling).
"""

import jax
import jax.numpy as jnp

__all__ = ["extend_with_decoupled_weight_decay"]


def extend_with_decoupled_weight_decay(base_optimizer):
    """Returns a subclass of ``base_optimizer`` taking an extra
    ``coeff`` (decay coefficient) and optional
    ``apply_decay_param_fun(name) -> bool`` filter. After the base
    update, every selected parameter decays against its pre-update
    value: ``p <- p - lr * coeff * p_prev`` (decoupled decay — never
    routed through the gradient/moments, the point of the reference's
    DecoupledWeightDecay)."""

    class OptimizerWithDecoupledWeightDecay(base_optimizer):
        def __init__(self, *args, coeff=0.0,
                     apply_decay_param_fun=None, **kwargs):
            super().__init__(*args, **kwargs)
            self._coeff = float(coeff)
            self._decay_fun = apply_decay_param_fun

        def apply_gradients(self, params, grads, state, param_meta=None):
            prev = params
            params, state = super().apply_gradients(
                params, grads, state, param_meta=param_meta)
            if not self._coeff:
                return params, state
            lr = self._lr_value(state["step"].astype(jnp.float32))
            if self._decay_fun is None:
                params = jax.tree.map(
                    lambda p, p0: p - lr * self._coeff * p0, params, prev)
            else:
                flatp, treedef = jax.tree_util.tree_flatten_with_path(
                    params)
                flat0 = jax.tree.leaves(prev)
                out = []
                for (path, p), p0 in zip(flatp, flat0):
                    name = "/".join(str(getattr(k, "key", k))
                                    for k in path)
                    out.append(p - lr * self._coeff * p0
                               if self._decay_fun(name) else p)
                params = jax.tree_util.tree_unflatten(treedef, out)
            return params, state

    OptimizerWithDecoupledWeightDecay.__name__ = (
        f"{base_optimizer.__name__}WithDecoupledWeightDecay")
    return OptimizerWithDecoupledWeightDecay
