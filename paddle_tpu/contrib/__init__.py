"""Contrib toolkits (parity: python/paddle/fluid/contrib — AMP lives in
paddle_tpu.amp; quantization/slim here)."""

from paddle_tpu.contrib import quant
from paddle_tpu.contrib import slim

__all__ = ["quant", "slim"]
