"""Contrib toolkits (parity: python/paddle/fluid/contrib — AMP lives in
paddle_tpu.amp; quantization/slim here)."""

from paddle_tpu.contrib import quant

__all__ = ["quant"]
