"""Contrib toolkits (parity: python/paddle/fluid/contrib — AMP lives in
paddle_tpu.amp; everything else here: quantization/slim, op-frequency +
model stats, decoupled weight decay, contrib layers (fused elementwise
activation, basic_gru/basic_lstm), beam-search decoder helper, HDFS +
lookup-table utils, and the deprecated Trainer/Inferencer facade)."""

from paddle_tpu.contrib import decoder
from paddle_tpu.contrib import extend_optimizer
from paddle_tpu.contrib import layers
from paddle_tpu.contrib import model_stat
from paddle_tpu.contrib import nas
from paddle_tpu.contrib import op_frequence
from paddle_tpu.contrib import quant
from paddle_tpu.contrib import reader
from paddle_tpu.contrib import slim
from paddle_tpu.contrib import trainer
from paddle_tpu.contrib import utils
from paddle_tpu.contrib.extend_optimizer import (
    extend_with_decoupled_weight_decay,
)
from paddle_tpu.contrib.model_stat import summary
from paddle_tpu.contrib.op_frequence import op_freq_statistic

__all__ = ["quant", "slim", "nas", "decoder", "extend_optimizer", "layers",
           "reader",
           "model_stat", "op_frequence", "trainer", "utils",
           "extend_with_decoupled_weight_decay", "summary",
           "op_freq_statistic"]
