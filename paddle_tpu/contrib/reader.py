"""contrib.reader parity.

Parity: python/paddle/fluid/contrib/reader/{distributed_reader.py
(distributed_batch_reader), ctr_reader.py (ctr_reader)}. The reference's
ctr_reader is a C++ reader op pipeline (operators/reader/ctr_reader);
here it is the native threaded loader (native/src/data_pipeline.cc) +
per-line parsing, yielding ready feed batches.
"""

import os

import numpy as np

__all__ = ["distributed_batch_reader", "ctr_reader"]


def distributed_batch_reader(batch_reader):
    """Shard a batch reader across trainers by round-robin on batch
    index (PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID env contract, ref
    distributed_reader.py)."""
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    if trainer_id >= trainers_num:
        raise ValueError(f"PADDLE_TRAINER_ID {trainer_id} >= "
                         f"PADDLE_TRAINERS_NUM {trainers_num}")

    def sharded():
        for i, batch in enumerate(batch_reader()):
            if i % trainers_num == trainer_id:
                yield batch
    return sharded


def _parse_csv(line, dense_slot_index, sparse_slot_index):
    cols = line.strip().split(",")
    label = np.int64(cols[0])
    dense = [np.float32(cols[i]) for i in dense_slot_index]
    sparse = [np.int64(cols[i]) for i in sparse_slot_index]
    return label, dense, sparse


def _parse_svm(line, slots):
    # "label slot:feasign slot:feasign ..." — grouped per slot id
    parts = line.strip().split()
    label = np.int64(parts[0])
    by_slot = {s: [] for s in slots}
    for tok in parts[1:]:
        sid, val = tok.split(":", 1)
        if sid in by_slot:
            by_slot[sid].append(np.int64(val))
    return label, by_slot


def ctr_reader(feed_dict, file_type, file_format, dense_slot_index,
               sparse_slot_index, capacity, thread_num, batch_size,
               file_list, slots, name=None):
    """contrib.reader.ctr_reader parity: a batched reader over CTR
    text shards. file_type: plain|gzip; file_format: csv|svm.

    Returns a reader callable yielding
    (label [B,1], dense [B, n_dense] float32,
     sparse: one [B, max_per_slot] int64 array per sparse slot padded
     with -1) — the dense-padded TPU form of the reference's
    LoDTensor outputs.
    """
    if file_type not in ("plain", "gzip"):
        raise ValueError(f"file_type must be plain|gzip, got {file_type}")
    if file_format not in ("csv", "svm"):
        raise ValueError(f"file_format must be csv|svm, got {file_format}")

    def lines():
        import gzip
        for path in file_list:
            opener = gzip.open if file_type == "gzip" else open
            with opener(path, "rt") as f:
                yield from f

    def reader():
        buf = []
        for line in lines():
            if not line.strip():
                continue
            buf.append(line)
            if len(buf) == batch_size:
                yield _batch(buf)
                buf = []
        if buf:
            yield _batch(buf)

    def _batch(lines_):
        if file_format == "csv":
            parsed = [_parse_csv(l, dense_slot_index, sparse_slot_index)
                      for l in lines_]
            label = np.array([p[0] for p in parsed], np.int64)[:, None]
            dense = np.array([p[1] for p in parsed], np.float32)
            # one [B, 1] int64 array PER sparse slot, matching the SVM
            # branch and the reference's per-slot LoDTensor outputs
            # (ref: operators/reader/ctr_reader.h one tensor per slot)
            sparse = np.array([p[2] for p in parsed], np.int64)
            return (label, dense) + tuple(
                sparse[:, i:i + 1] for i in range(sparse.shape[1]))
        parsed = [_parse_svm(l, slots) for l in lines_]
        label = np.array([p[0] for p in parsed], np.int64)[:, None]
        outs = [label]
        for s in slots:
            maxn = max(max((len(p[1][s]) for p in parsed), default=1), 1)
            arr = np.full((len(parsed), maxn), -1, np.int64)
            for i, p in enumerate(parsed):
                vals = p[1][s]
                arr[i, :len(vals)] = vals
            outs.append(arr)
        return tuple(outs)

    return reader
