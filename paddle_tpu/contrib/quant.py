"""Quantization toolkit: QAT program rewriting + post-training quant.

Parity: python/paddle/fluid/contrib/slim/quantization/quantization_pass.py
(QuantizationTransformPass inserts fake_quant/dequant around quantizable
ops in the IR graph) and contrib/quantize/quantize_transpiler.py.

TPU shape: the static `QuantizeTranspiler` rewrites the Program in place
(our Program IS the IR here — no separate Graph form); eager/functional
training uses `fake_quant_params` inside the loss. Gradients flow through
the inserted ops via the STE custom_vjp in ops/quantize.py, so no grad
registration step is needed (the reference patches grads in the pass).
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import quantize as Q
from paddle_tpu.static.passes import (BlockRewriter, ProgramPass,
                                      match_ops)

__all__ = ["QuantizeTranspiler", "fake_quant_params",
           "post_training_quantize", "dequantize_params",
           "calibrate_activations", "QuantizationFreezePass",
           "ConvertToInt8Pass", "quantize_program_int8"]

_QUANTIZABLE = ("mul", "matmul", "conv2d", "depthwise_conv2d")


def _quantize_weight_in_scope(scope, name, bits):
    """abs-max quantize a scope weight to integer storage in place;
    returns the fp32 scale (shared by freeze + convert passes)."""
    var = scope.find_var(name)
    if var is None:
        raise KeyError(f"weight {name!r} not initialized in scope")
    w = np.asarray(var, np.float32)
    scale = float(np.max(np.abs(w))) if w.size else 0.0
    scope.set_var(name, np.asarray(Q.quantize_linear(
        w, max(scale, 1e-12), bit_length=bits)))
    return scale


class QuantizeTranspiler(ProgramPass):
    """Insert fake quant-dequant ops before every quantizable op's tensor
    inputs in a static Program (QuantizationTransformPass parity —
    weight_quantize_type/activation_quantize_type 'abs_max').
    Expressed on the pass framework (static/passes.py): match
    quantizable ops, queue fake-quant insertions, rewire, commit."""

    name = "quantize_transform"

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_op_type=_QUANTIZABLE):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.op_types = tuple(quantizable_op_type)

    def apply(self, program):
        rw = BlockRewriter(program)
        blk = rw.block
        quantized = {}       # var name -> quant-dequant output name
        for i, op in match_ops(program, self.op_types):
            for slot, names in op.inputs.items():
                rewritten = []
                for name in names:
                    if name not in quantized:
                        var = blk.vars.get(name)
                        is_w = var is not None and getattr(
                            var, "persistable", False)
                        bits = (self.weight_bits if is_w
                                else self.activation_bits)
                        qname = f"{name}.quant_dequant"
                        rw.create_var(
                            qname,
                            shape=var.shape if var is not None else None,
                            dtype=var.dtype if var is not None
                            else "float32")
                        rw.create_var(f"{name}.quant_scale", shape=[],
                                      dtype="float32")
                        rw.insert_before(i, rw.make_op(
                            "fake_quantize_dequantize_abs_max",
                            inputs={"X": [name]},
                            outputs={"Out": [qname,
                                             f"{name}.quant_scale"]},
                            attrs={"bit_length": bits}))
                        quantized[name] = qname
                    rewritten.append(quantized[name])
                op.inputs[slot] = rewritten
        return rw.commit()

    # original API name, kept
    transpile = apply


def fake_quant_params(params, bit_length=8, channel_wise=False):
    """Eager QAT: quant-dequant every weight leaf (STE gradients flow).
    Call inside the loss: loss_fn(fake_quant_params(params), ...)."""
    def qd(p):
        if p.ndim == 0:
            return p
        if channel_wise and p.ndim >= 2:
            out, _ = Q.fake_channel_wise_quantize_dequantize_abs_max(
                p, bit_length=bit_length)
        else:
            out, _ = Q.fake_quantize_dequantize_abs_max(
                p, bit_length=bit_length)
        return out
    return jax.tree_util.tree_map(qd, params)


def post_training_quantize(params, bit_length=8):
    """PTQ: pytree of float weights → (list of (int values, fp32 scale)
    leaves in flatten order, treedef) — weight-only abs-max
    (contrib/slim post-training strategy parity). Integer width follows
    bit_length via ops/quantize.quantize_linear."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    quantized = []
    for p in flat:
        p = np.asarray(p, np.float32)
        scale = float(np.max(np.abs(p))) if p.size else 0.0
        q = np.asarray(Q.quantize_linear(p, scale, bit_length=bit_length))
        quantized.append((q, scale))
    return quantized, treedef


def dequantize_params(quantized, treedef, bit_length=8):
    """Inverse of post_training_quantize."""
    flat = [np.asarray(Q.dequantize_linear(jnp.asarray(q),
                                           max(s, 1e-12),
                                           bit_length=bit_length))
            for q, s in quantized]
    return jax.tree_util.tree_unflatten(treedef, flat)


def calibrate_activations(exe, program, feed_batches, scope=None,
                          quantizable_op_type=_QUANTIZABLE,
                          strategy="abs_max", moving_rate=0.9):
    """Activation-range calibration from sample batches — the role of
    the reference's int8 calibrator (inference/tensorrt/
    trt_int8_calibrator.cc feeding scale ranges to the engine, and
    slim PTQ's activation pass). Runs the program over the calibration
    feeds, fetching every ACTIVATION var that feeds a quantizable op,
    and returns {var name: scale}.

    strategy 'abs_max' takes the max |x| over all batches;
    'moving_average_abs_max' follows the reference's EMA
    (quantization_pass.py moving-average scale) for outlier-robust
    ranges."""
    from paddle_tpu.static.executor import global_scope
    scope = scope or global_scope()
    blk = program.global_block()
    act_names = []
    for op in blk.ops:
        if op.type not in quantizable_op_type:
            continue
        for names in op.inputs.values():
            for name in names:
                base = name.split(".quant_dequant")[0]
                var = blk.vars.get(base)
                if var is not None and getattr(var, "persistable", False):
                    continue          # weights calibrate from values
                if base not in act_names:
                    act_names.append(base)
    scales = {}
    for feed in feed_batches:
        vals = exe.run(program, feed=feed, fetch_list=act_names,
                       scope=scope)
        for name, v in zip(act_names, vals):
            m = float(np.max(np.abs(np.asarray(v)))) if np.asarray(
                v).size else 0.0
            if strategy == "moving_average_abs_max":
                prev = scales.get(name)
                scales[name] = m if prev is None else (
                    moving_rate * prev + (1 - moving_rate) * m)
            else:
                scales[name] = max(scales.get(name, 0.0), m)
    return scales


class QuantizationFreezePass(ProgramPass):
    """Freeze a fake-quant (QAT) program into an int8 inference
    program (ref: contrib/slim/quantization/quantization_pass.py
    QuantizationFreezePass): strips the fake quant-dequant ops,
    quantizes every trained weight to integers IN THE SCOPE (abs-max
    of the trained value — the reference reads the same from its
    quantized var), and rewrites each quantizable op into its integer
    kernel (quantized_mul / quantized_conv2d) carrying the weight
    scale and the calibrated activation scale as attributes.

    ``act_scales`` maps ORIGINAL activation var names to calibrated
    ranges (see calibrate_activations). Activations quantize on the
    fly inside the integer kernels at those scales, so the frozen
    program is a pure static Program that the Executor / inference
    Predictor runs like any other."""

    name = "quantization_freeze"
    _REWRITE = {"mul": "quantized_mul", "matmul": "quantized_mul",
                "conv2d": "quantized_conv2d",
                "depthwise_conv2d": "quantized_conv2d"}
    # attrs each integer kernel accepts: anything else on the op means
    # semantics the kernel cannot express — the op stays float
    _KERNEL_ATTRS = {
        "quantized_mul": {"x_num_col_dims"},
        "quantized_conv2d": {"stride", "padding", "dilation", "groups",
                             "data_format"},
    }
    # attr values that are semantically the kernel's default: safe to
    # drop rather than refuse (matmul's wrapper records these even
    # when unused)
    _DROPPABLE_DEFAULTS = {"y_num_col_dims": 1, "transpose_x": False,
                           "transpose_y": False, "alpha": 1.0,
                           "name": None}

    def __init__(self, scope=None, weight_bits=8, activation_bits=8,
                 act_scales=None):
        self.scope = scope
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_scales = dict(act_scales or {})
        self.weight_scales = {}

    def _base(self, name):
        return name.split(".quant_dequant")[0]

    def _plan_op(self, op, blk, scope):
        """Decide how one quantizable op freezes WITHOUT mutating
        anything. Returns (kernel, attrs, act_name, w_name) for a
        rewrite, or None to leave the op float."""
        kernel = self._REWRITE[op.type]
        attrs, unsupported = {}, False
        for k, v in op.attrs.items():
            if k in self._KERNEL_ATTRS[kernel]:
                attrs[k] = v
            elif (k in self._DROPPABLE_DEFAULTS
                  and v == self._DROPPABLE_DEFAULTS[k]):
                pass                  # recorded default: fold away
            else:
                unsupported = True    # e.g. transpose_y=True
        bases = [self._base(n) for names in op.inputs.values()
                 for n in names]

        def is_weight(base):
            var = blk.vars.get(base)
            return var is not None and getattr(var, "persistable",
                                               False)
        # the integer kernels compute act @ weight: only the standard
        # [activation, weight] operand order is expressible — a
        # weight-first matmul (w @ x) must stay float, NOT be silently
        # reordered
        if (unsupported or len(bases) != 2 or is_weight(bases[0])
                or not is_weight(bases[1])):
            return None
        act_name, w_name = bases
        if op.type == "depthwise_conv2d":
            # the float op injects feature_group_count=C internally;
            # the frozen op must carry it. Only the multiplier-1
            # layout (C, 1, kh, kw) is derivable from the filter
            # alone — otherwise stay float.
            w_shape = np.asarray(scope.find_var(w_name)).shape
            if len(w_shape) == 4 and w_shape[1] == 1:
                attrs["groups"] = int(w_shape[0])
            else:
                return None
        if act_name not in self.act_scales:
            raise KeyError(
                f"no calibrated scale for activation {act_name!r} "
                f"feeding {op.type} — run calibrate_activations over "
                f"sample batches first")
        return kernel, attrs, act_name, w_name

    def apply(self, program):
        from paddle_tpu.static.executor import global_scope
        scope = self.scope or global_scope()
        rw = BlockRewriter(program)
        blk = rw.block
        # PLAN first (validates every op incl. calibrated scales),
        # mutate second: a missing scale must raise before any weight
        # in the scope has been converted to integers — a partial
        # freeze would leave a float program over int8 weights
        plans = {}
        for i, op in match_ops(program, tuple(self._REWRITE)):
            plans[i] = self._plan_op(op, blk, scope)
        # A persistable weight may also feed ops that stay float (an
        # unplanned matmul, a non-quantizable consumer, a save op):
        # integer storage in the scope would hand those consumers
        # ~2^(bits-1)x-magnitude values with no dequantize. A weight
        # freezes only when EVERY surviving consumer freezes with it.
        float_read = set()
        for i, op in enumerate(blk.ops):
            if op.type == "fake_quantize_dequantize_abs_max":
                continue              # stripped below, not a consumer
            plan = plans.get(i)
            frozen_w = plan[3] if plan is not None else None
            for names in op.inputs.values():
                for n in names:
                    base = self._base(n)
                    if base != frozen_w:
                        float_read.add(base)
        for i, plan in list(plans.items()):
            if plan is not None and plan[3] in float_read:
                plans[i] = None       # shared with a float reader
        for i, op in enumerate(blk.ops):
            if op.type == "fake_quantize_dequantize_abs_max":
                rw.remove(i)          # stripped: scales fold below
            elif plans.get(i) is not None:
                kernel, attrs, act_name, w_name = plans[i]
                w_scale = self._freeze_weight(scope, w_name)
                attrs["x_scale"] = float(self.act_scales[act_name])
                attrs["w_scale"] = float(w_scale)
                attrs["bit_length"] = self.activation_bits
                if self.weight_bits != self.activation_bits:
                    attrs["w_bit_length"] = self.weight_bits
                rw.replace(i, rw.make_op(
                    kernel, inputs={"X": [act_name, w_name]},
                    outputs=dict(op.outputs), attrs=attrs))
            else:
                # float op (incl. unplanned quantizable ops): rewire
                # any stray .quant_dequant reads back to base
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [self._base(n) for n in names]
        return rw.commit()

    def _freeze_weight(self, scope, name):
        if name in self.weight_scales:
            return self.weight_scales[name]
        scale = _quantize_weight_in_scope(scope, name,
                                          self.weight_bits)
        self.weight_scales[name] = scale
        return scale


class ConvertToInt8Pass(ProgramPass):
    """Storage-only conversion (ref: quantization_pass.py
    ConvertToInt8Pass): quantize every persistable weight consumed by
    a quantizable op to int8 in the scope WITHOUT rewriting ops — used
    when the runtime dequantizes on load. Returns {weight: scale}."""

    name = "convert_to_int8"

    def __init__(self, scope=None, weight_bits=8,
                 quantizable_op_type=_QUANTIZABLE):
        self.scope = scope
        self.weight_bits = weight_bits
        self.op_types = tuple(quantizable_op_type)

    def apply(self, program):
        from paddle_tpu.static.executor import global_scope
        scope = self.scope or global_scope()
        blk = program.global_block()
        scales = {}
        for _, op in match_ops(program, self.op_types):
            for names in op.inputs.values():
                for name in names:
                    var = blk.vars.get(name)
                    if var is None or not getattr(var, "persistable",
                                                  False):
                        continue
                    if name in scales:
                        continue
                    scales[name] = _quantize_weight_in_scope(
                        scope, name, self.weight_bits)
        return scales


def quantize_program_int8(exe, program, feed_batches, scope=None,
                          weight_bits=8, activation_bits=8,
                          quantizable_op_type=_QUANTIZABLE,
                          strategy="abs_max"):
    """One-call post-training int8 quantization: calibrate activation
    ranges from ``feed_batches``, then freeze the program (weights ->
    int8 in scope, quantizable ops -> integer kernels). Works on a
    plain fp32 program (PTQ) or a QAT-transpiled one after training
    (the fake ops are stripped and their role folds into the scales).
    Returns the frozen program (rewritten in place)."""
    scales = calibrate_activations(
        exe, program, feed_batches, scope=scope,
        quantizable_op_type=quantizable_op_type, strategy=strategy)
    return QuantizationFreezePass(
        scope=scope, weight_bits=weight_bits,
        activation_bits=activation_bits, act_scales=scales).apply(program)
