"""Quantization toolkit: QAT program rewriting + post-training quant.

Parity: python/paddle/fluid/contrib/slim/quantization/quantization_pass.py
(QuantizationTransformPass inserts fake_quant/dequant around quantizable
ops in the IR graph) and contrib/quantize/quantize_transpiler.py.

TPU shape: the static `QuantizeTranspiler` rewrites the Program in place
(our Program IS the IR here — no separate Graph form); eager/functional
training uses `fake_quant_params` inside the loss. Gradients flow through
the inserted ops via the STE custom_vjp in ops/quantize.py, so no grad
registration step is needed (the reference patches grads in the pass).
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import quantize as Q
from paddle_tpu.static.program import Operator

__all__ = ["QuantizeTranspiler", "fake_quant_params",
           "post_training_quantize", "dequantize_params"]

_QUANTIZABLE = ("mul", "matmul", "conv2d", "depthwise_conv2d")


class QuantizeTranspiler:
    """Insert fake quant-dequant ops before every quantizable op's tensor
    inputs in a static Program (QuantizationTransformPass parity —
    weight_quantize_type/activation_quantize_type 'abs_max')."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_op_type=_QUANTIZABLE):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.op_types = tuple(quantizable_op_type)

    def transpile(self, program):
        blk = program.global_block()
        new_ops = []
        quantized = {}       # var name -> quant-dequant output name
        for op in blk.ops:
            if op.type in self.op_types:
                for slot, names in op.inputs.items():
                    rewritten = []
                    for name in names:
                        if name not in quantized:
                            var = blk.vars.get(name)
                            is_w = var is not None and getattr(
                                var, "persistable", False)
                            bits = (self.weight_bits if is_w
                                    else self.activation_bits)
                            qname = f"{name}.quant_dequant"
                            blk.create_var(
                                name=qname,
                                shape=var.shape if var is not None else None,
                                dtype=var.dtype if var is not None
                                else "float32")
                            sname = f"{name}.quant_scale"
                            blk.create_var(name=sname, shape=[],
                                           dtype="float32")
                            qop = Operator(
                                blk, "fake_quantize_dequantize_abs_max",
                                inputs={"X": [name]},
                                outputs={"Out": [qname, sname]},
                                attrs={"bit_length": bits})
                            new_ops.append(qop)
                            quantized[name] = qname
                        rewritten.append(quantized[name])
                    op.inputs[slot] = rewritten
            new_ops.append(op)
        blk.ops = new_ops
        program._bump()
        return program


def fake_quant_params(params, bit_length=8, channel_wise=False):
    """Eager QAT: quant-dequant every weight leaf (STE gradients flow).
    Call inside the loss: loss_fn(fake_quant_params(params), ...)."""
    def qd(p):
        if p.ndim == 0:
            return p
        if channel_wise and p.ndim >= 2:
            out, _ = Q.fake_channel_wise_quantize_dequantize_abs_max(
                p, bit_length=bit_length)
        else:
            out, _ = Q.fake_quantize_dequantize_abs_max(
                p, bit_length=bit_length)
        return out
    return jax.tree_util.tree_map(qd, params)


def post_training_quantize(params, bit_length=8):
    """PTQ: pytree of float weights → (list of (int values, fp32 scale)
    leaves in flatten order, treedef) — weight-only abs-max
    (contrib/slim post-training strategy parity). Integer width follows
    bit_length via ops/quantize.quantize_linear."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    quantized = []
    for p in flat:
        p = np.asarray(p, np.float32)
        scale = float(np.max(np.abs(p))) if p.size else 0.0
        q = np.asarray(Q.quantize_linear(p, scale, bit_length=bit_length))
        quantized.append((q, scale))
    return quantized, treedef


def dequantize_params(quantized, treedef, bit_length=8):
    """Inverse of post_training_quantize."""
    flat = [np.asarray(Q.dequantize_linear(jnp.asarray(q),
                                           max(s, 1e-12),
                                           bit_length=bit_length))
            for q, s in quantized]
    return jax.tree_util.tree_unflatten(treedef, flat)
