"""Model-slim toolkit: pruning, distillation, sensitivity analysis.

Parity targets: python/paddle/fluid/contrib/slim/ — prune strategies
(slim/prune: SensitivePruneStrategy, ratio pruning of conv/fc weights),
distillation losses (slim/distillation/distillation_strategy.py +
distiller.py: FSPDistiller, L2Distiller, SoftLabelDistiller; the fsp op
operators/fsp_op.cc), and the sensitivity-analysis loop the reference's
auto-pruner runs.

TPU-native shape: pruning is a pure function over the param pytree
(mask + re-apply every step keeps XLA shapes static — actual sparsity
on TPU is realized by the compiler/quantizer downstream, so masks ARE
the artifact, exactly like the reference's parameter-backup + mask
apply); distillation losses are plain jittable functions usable in any
loss composition.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce

__all__ = [
    "magnitude_prune_mask", "structured_prune_mask", "apply_masks",
    "prune_ratio", "sensitivity", "Pruner",
    "soft_label_distill_loss", "l2_distill_loss", "fsp_matrix",
    "fsp_distill_loss",
]


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------
def magnitude_prune_mask(w, ratio):
    """0/1 mask zeroing the smallest-|w| ``ratio`` fraction of entries
    (slim's unstructured ratio pruning)."""
    enforce(0.0 <= ratio < 1.0, "ratio in [0,1)")
    k = int(np.floor(ratio * w.size))
    if k == 0:
        return jnp.ones_like(w)
    # exactly-k by sorted index, not a threshold compare: with tied
    # magnitudes (zero-init tensors) a threshold would drop every tie
    flat = jnp.abs(w.reshape(-1))
    drop = jnp.argsort(flat)[:k]
    mask = jnp.ones(w.size, w.dtype).at[drop].set(0)
    return mask.reshape(w.shape)


def structured_prune_mask(w, ratio, axis=-1):
    """Channel pruning: zero whole slices along ``axis`` with smallest
    L1 norm (slim's filter pruning of conv output channels)."""
    enforce(0.0 <= ratio < 1.0, "ratio in [0,1)")
    axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    norms = jnp.sum(jnp.abs(w), axis=axes)
    n = norms.shape[0]
    k = int(np.floor(ratio * n))
    if k == 0:
        return jnp.ones_like(w)
    drop = jnp.argsort(norms)[:k]          # exactly-k (tie-safe)
    keep = jnp.ones(n, w.dtype).at[drop].set(0)
    shape = [1] * w.ndim
    shape[axis % w.ndim] = n
    return jnp.broadcast_to(keep.reshape(shape), w.shape)


def apply_masks(params, masks):
    """Elementwise-apply a (possibly partial) mask tree to a param tree."""
    def apply_one(path_params, path_masks):
        return jax.tree.map(
            lambda p, m: p * m if m is not None else p,
            path_params, path_masks, is_leaf=lambda x: x is None)
    return apply_one(params, masks)


def prune_ratio(masks):
    """Fraction of weights zeroed across all masked tensors."""
    leaves = [m for m in jax.tree.leaves(masks) if m is not None]
    if not leaves:
        return 0.0
    total = sum(m.size for m in leaves)
    kept = sum(float(jnp.sum(m)) for m in leaves)
    return 1.0 - kept / total


def sensitivity(eval_fn, params, select, ratios=(0.1, 0.3, 0.5, 0.7)):
    """Per-tensor prune sensitivity (slim's SensitivePruneStrategy
    analysis loop): for each param chosen by ``select(path_name)``,
    evaluate ``eval_fn(pruned_params)`` at each ratio.

    Returns {param_name: {ratio: metric}}. eval_fn is typically
    validation loss/accuracy on a held-out batch."""
    pairs = jax.tree_util.tree_flatten_with_path(params)[0]
    flat = {jax.tree_util.keystr(kp): (kp, v) for kp, v in pairs}
    out = {}
    for name, (kp, w) in flat.items():
        if not select(name):
            continue
        res = {}
        for r in ratios:
            mask = magnitude_prune_mask(w, r)

            def sub(kp2, v):
                return v * mask if jax.tree_util.keystr(kp2) == name else v
            pruned = jax.tree_util.tree_map_with_path(sub, params)
            res[float(r)] = float(eval_fn(pruned))
        out[name] = res
    return out


class Pruner:
    """Stateful convenience wrapper (slim Pruner parity): compute masks
    once, re-apply after every optimizer step so pruned weights stay
    zero through training."""

    def __init__(self, ratio, structured=False, axis=-1,
                 select=lambda name: True):
        self.ratio = ratio
        self.structured = structured
        self.axis = axis
        self.select = select
        self.masks = None

    def compute_masks(self, params):
        def one(kp, w):
            name = jax.tree_util.keystr(kp)
            if not self.select(name) or w.ndim < 1:
                return None
            if self.structured and w.ndim >= 2:
                return structured_prune_mask(w, self.ratio, self.axis)
            return magnitude_prune_mask(w, self.ratio)
        self.masks = jax.tree_util.tree_map_with_path(one, params)
        return self.masks

    def prune(self, params):
        if self.masks is None:
            self.compute_masks(params)
        return apply_masks(params, self.masks)


# ---------------------------------------------------------------------------
# distillation (slim/distillation/distiller.py parity)
# ---------------------------------------------------------------------------
def soft_label_distill_loss(student_logits, teacher_logits,
                            temperature=2.0):
    """SoftLabelDistiller: KL(teacher_T || student_T) * T^2 (Hinton)."""
    t = jax.nn.softmax(teacher_logits / temperature, axis=-1)
    log_s = jax.nn.log_softmax(student_logits / temperature, axis=-1)
    log_t = jax.nn.log_softmax(teacher_logits / temperature, axis=-1)
    kl = jnp.sum(t * (log_t - log_s), axis=-1)
    return jnp.mean(kl) * temperature ** 2


def l2_distill_loss(student_feat, teacher_feat):
    """L2Distiller: mean squared feature-map distance."""
    return jnp.mean((student_feat - teacher_feat) ** 2)


def fsp_matrix(a, b):
    """operators/fsp_op.cc parity — delegates to ops.misc.fsp_matrix
    (NCHW, like the rest of paddle_tpu.ops): [N,Ca,H,W] x [N,Cb,H,W]
    -> [N, Ca, Cb]."""
    from paddle_tpu.ops.misc import fsp_matrix as _fsp
    return _fsp(a, b)


def fsp_distill_loss(student_pair, teacher_pair):
    """FSPDistiller: L2 between student and teacher FSP matrices.
    Each pair is (feature_in, feature_out) from the same stage."""
    gs = fsp_matrix(*student_pair)
    gt = fsp_matrix(*teacher_pair)
    return jnp.mean((gs - gt) ** 2)


# ---------------------------------------------------------------------------
# Compressor: epoch-driven compression sessions
# ---------------------------------------------------------------------------
class Context:
    """Mutable session state threaded through strategy callbacks
    (ref: slim/core/compressor.py Context — epoch counter, graph,
    eval history; here the functional analogs: params pytree, masks,
    per-epoch eval results)."""

    def __init__(self, params, optimizer):
        self.params = params
        self.optimizer = optimizer
        self.opt_state = None
        self.epoch = 0
        self.masks = None            # active prune masks (pytree)
        self.loss_wrappers = []      # applied in order around base loss
        self.eval_history = []


class Strategy:
    """Strategy base (ref: slim/core/strategy.py): callbacks fire by
    epoch window [start_epoch, end_epoch]."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass


class PruneStrategy(Strategy):
    """Scheduled magnitude pruning inside the train loop (ref:
    slim/prune/prune_strategy.py SensitivePruneStrategy's
    epoch-scheduled ratio ramp): the prune ratio ramps linearly from 0
    at ``start_epoch`` to ``target_ratio`` at ``end_epoch``; each epoch
    recomputes masks at the scheduled ratio and the Compressor
    re-applies them after every optimizer step (the reference's
    backup+mask mechanism, functionally)."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=5,
                 target_ratio=0.5, select=None):
        super().__init__(start_epoch, end_epoch)
        self.target_ratio = target_ratio
        self.select = select or (lambda kp, w: getattr(w, "ndim", 0) >= 2)
        self.pruner = pruner
        self.ratios = []

    def _ratio_at(self, epoch):
        if epoch < self.start_epoch:
            return 0.0
        span = max(self.end_epoch - self.start_epoch, 1)
        frac = min((epoch - self.start_epoch) / span, 1.0)
        return self.target_ratio * frac

    def on_epoch_begin(self, context):
        ratio = self._ratio_at(context.epoch)
        self.ratios.append(ratio)
        if ratio <= 0.0:
            return
        if self.pruner is not None:
            # honor the user's Pruner config (structured/axis/select)
            # at this epoch's scheduled ratio
            self.pruner.ratio = ratio
            mine = self.pruner.compute_masks(context.params)
        else:
            def mask_one(kp, w):
                if getattr(w, "ndim", 0) >= 2 and self.select(kp, w):
                    return magnitude_prune_mask(np.asarray(w), ratio)
                return None         # unselected: no mask (None leaf)
            mine = jax.tree_util.tree_map_with_path(
                mask_one, context.params)
        # MERGE with masks other strategies may have installed this
        # epoch (two windows pruning different param subsets compose);
        # None means unmasked on either side
        if context.masks is None:
            context.masks = mine
        else:
            def merge(old, new):
                if old is None:
                    return new
                if new is None:
                    return old
                return old * new
            context.masks = jax.tree.map(
                merge, context.masks, mine,
                is_leaf=lambda x: x is None)
        context.params = apply_masks(context.params, context.masks)


class DistillationStrategy(Strategy):
    """Teacher-student distillation window (ref: slim/distillation/
    distillation_strategy.py + distiller.py): within
    [start_epoch, end_epoch) the train loss becomes
    base + distill_weight * distill(student_logits, teacher_logits).
    ``teacher_fn(batch) -> teacher outputs`` runs OUTSIDE the grad
    (stop-gradient teacher, like the reference's merged frozen teacher
    graph); ``distill_loss(student_out, teacher_out)`` defaults to
    soft-label distillation."""

    def __init__(self, teacher_fn, student_out_fn, start_epoch=0,
                 end_epoch=1000, distill_loss=None, distill_weight=1.0):
        super().__init__(start_epoch, end_epoch)
        self.teacher_fn = teacher_fn
        self.student_out_fn = student_out_fn
        self.distill_loss = distill_loss or soft_label_distill_loss
        self.distill_weight = distill_weight
        self._active = False

    def on_compression_begin(self, context):
        strategy = self

        def wrap(base_loss_fn):
            def loss_fn(params, batch):
                loss = base_loss_fn(params, batch)
                if not strategy._active:
                    return loss
                t_out = jax.lax.stop_gradient(strategy.teacher_fn(batch))
                s_out = strategy.student_out_fn(params, batch)
                return loss + strategy.distill_weight * \
                    strategy.distill_loss(s_out, t_out)
            return loss_fn
        context.loss_wrappers.append(wrap)

    def on_epoch_begin(self, context):
        self._active = (self.start_epoch <= context.epoch
                        < self.end_epoch)


class Compressor:
    """Config-driven compression session (ref: slim/core/compressor.py
    Compressor.run): an epoch loop owning the jitted train step, with
    strategies hooked at compression/epoch boundaries. Functional
    eager tier: ``loss_fn(params, batch) -> scalar`` and
    ``batches()`` (a fresh iterator per epoch) define training;
    ``eval_fn(params) -> float`` records per-epoch metrics.

    Pruning strategies set ``context.masks``; the step re-applies them
    after every optimizer update so pruned weights stay exactly zero
    (the reference re-masks via its backup mechanism). Distillation
    strategies wrap the loss. ``run()`` returns (params, context).
    """

    def __init__(self, params, optimizer, loss_fn, batches, eval_fn=None,
                 strategies=(), epochs=1):
        self.params = params
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.batches = batches
        self.eval_fn = eval_fn
        self.strategies = list(strategies)
        self.epochs = epochs

    def add_strategy(self, s):
        self.strategies.append(s)
        return self

    def _make_step(self, loss_fn, masked):
        opt = self.optimizer
        if masked:
            @jax.jit
            def step(params, opt_state, masks, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                params, opt_state = opt.apply_gradients(params, grads,
                                                        opt_state)
                return loss, apply_masks(params, masks), opt_state
        else:
            @jax.jit
            def step(params, opt_state, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                params, opt_state = opt.apply_gradients(params, grads,
                                                        opt_state)
                return loss, params, opt_state
        return step

    def run(self):
        ctx = Context(self.params, self.optimizer)
        for s in self.strategies:
            s.on_compression_begin(ctx)
        base_loss = self.loss_fn
        for wrap in ctx.loss_wrappers:
            base_loss = wrap(base_loss)
        ctx.opt_state = self.optimizer.init(ctx.params)
        # strategy activation flags (e.g. a distillation window) are
        # Python state the traced loss closes over — steps are cached
        # PER activation signature so a flag flip retraces instead of
        # silently running the stale trace
        step_cache = {}
        for epoch in range(self.epochs):
            ctx.epoch = epoch
            for s in self.strategies:
                s.on_epoch_begin(ctx)
            # keyed by POSITION, not sorted: two strategies of one
            # class with swapped activation states must not collide
            key = (tuple(bool(getattr(s, "_active", False))
                         for s in self.strategies),
                   ctx.masks is not None)
            if key not in step_cache:
                step_cache[key] = self._make_step(base_loss,
                                                  ctx.masks is not None)
            step = step_cache[key]
            for batch in self.batches():
                if ctx.masks is None:
                    loss, ctx.params, ctx.opt_state = step(
                        ctx.params, ctx.opt_state, batch)
                else:
                    loss, ctx.params, ctx.opt_state = step(
                        ctx.params, ctx.opt_state, ctx.masks, batch)
            for s in self.strategies:
                s.on_epoch_end(ctx)
            if self.eval_fn is not None:
                ctx.eval_history.append(float(self.eval_fn(ctx.params)))
        for s in self.strategies:
            s.on_compression_end(ctx)
        return ctx.params, ctx


__all__ += ["Context", "Strategy", "PruneStrategy",
            "DistillationStrategy", "Compressor"]
