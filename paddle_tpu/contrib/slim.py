"""Model-slim toolkit: pruning, distillation, sensitivity analysis.

Parity targets: python/paddle/fluid/contrib/slim/ — prune strategies
(slim/prune: SensitivePruneStrategy, ratio pruning of conv/fc weights),
distillation losses (slim/distillation/distillation_strategy.py +
distiller.py: FSPDistiller, L2Distiller, SoftLabelDistiller; the fsp op
operators/fsp_op.cc), and the sensitivity-analysis loop the reference's
auto-pruner runs.

TPU-native shape: pruning is a pure function over the param pytree
(mask + re-apply every step keeps XLA shapes static — actual sparsity
on TPU is realized by the compiler/quantizer downstream, so masks ARE
the artifact, exactly like the reference's parameter-backup + mask
apply); distillation losses are plain jittable functions usable in any
loss composition.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce

__all__ = [
    "magnitude_prune_mask", "structured_prune_mask", "apply_masks",
    "prune_ratio", "sensitivity", "Pruner",
    "soft_label_distill_loss", "l2_distill_loss", "fsp_matrix",
    "fsp_distill_loss",
]


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------
def magnitude_prune_mask(w, ratio):
    """0/1 mask zeroing the smallest-|w| ``ratio`` fraction of entries
    (slim's unstructured ratio pruning)."""
    enforce(0.0 <= ratio < 1.0, "ratio in [0,1)")
    k = int(np.floor(ratio * w.size))
    if k == 0:
        return jnp.ones_like(w)
    # exactly-k by sorted index, not a threshold compare: with tied
    # magnitudes (zero-init tensors) a threshold would drop every tie
    flat = jnp.abs(w.reshape(-1))
    drop = jnp.argsort(flat)[:k]
    mask = jnp.ones(w.size, w.dtype).at[drop].set(0)
    return mask.reshape(w.shape)


def structured_prune_mask(w, ratio, axis=-1):
    """Channel pruning: zero whole slices along ``axis`` with smallest
    L1 norm (slim's filter pruning of conv output channels)."""
    enforce(0.0 <= ratio < 1.0, "ratio in [0,1)")
    axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    norms = jnp.sum(jnp.abs(w), axis=axes)
    n = norms.shape[0]
    k = int(np.floor(ratio * n))
    if k == 0:
        return jnp.ones_like(w)
    drop = jnp.argsort(norms)[:k]          # exactly-k (tie-safe)
    keep = jnp.ones(n, w.dtype).at[drop].set(0)
    shape = [1] * w.ndim
    shape[axis % w.ndim] = n
    return jnp.broadcast_to(keep.reshape(shape), w.shape)


def apply_masks(params, masks):
    """Elementwise-apply a (possibly partial) mask tree to a param tree."""
    def apply_one(path_params, path_masks):
        return jax.tree.map(
            lambda p, m: p * m if m is not None else p,
            path_params, path_masks, is_leaf=lambda x: x is None)
    return apply_one(params, masks)


def prune_ratio(masks):
    """Fraction of weights zeroed across all masked tensors."""
    leaves = [m for m in jax.tree.leaves(masks) if m is not None]
    if not leaves:
        return 0.0
    total = sum(m.size for m in leaves)
    kept = sum(float(jnp.sum(m)) for m in leaves)
    return 1.0 - kept / total


def sensitivity(eval_fn, params, select, ratios=(0.1, 0.3, 0.5, 0.7)):
    """Per-tensor prune sensitivity (slim's SensitivePruneStrategy
    analysis loop): for each param chosen by ``select(path_name)``,
    evaluate ``eval_fn(pruned_params)`` at each ratio.

    Returns {param_name: {ratio: metric}}. eval_fn is typically
    validation loss/accuracy on a held-out batch."""
    pairs = jax.tree_util.tree_flatten_with_path(params)[0]
    flat = {jax.tree_util.keystr(kp): (kp, v) for kp, v in pairs}
    out = {}
    for name, (kp, w) in flat.items():
        if not select(name):
            continue
        res = {}
        for r in ratios:
            mask = magnitude_prune_mask(w, r)

            def sub(kp2, v):
                return v * mask if jax.tree_util.keystr(kp2) == name else v
            pruned = jax.tree_util.tree_map_with_path(sub, params)
            res[float(r)] = float(eval_fn(pruned))
        out[name] = res
    return out


class Pruner:
    """Stateful convenience wrapper (slim Pruner parity): compute masks
    once, re-apply after every optimizer step so pruned weights stay
    zero through training."""

    def __init__(self, ratio, structured=False, axis=-1,
                 select=lambda name: True):
        self.ratio = ratio
        self.structured = structured
        self.axis = axis
        self.select = select
        self.masks = None

    def compute_masks(self, params):
        def one(kp, w):
            name = jax.tree_util.keystr(kp)
            if not self.select(name) or w.ndim < 1:
                return None
            if self.structured and w.ndim >= 2:
                return structured_prune_mask(w, self.ratio, self.axis)
            return magnitude_prune_mask(w, self.ratio)
        self.masks = jax.tree_util.tree_map_with_path(one, params)
        return self.masks

    def prune(self, params):
        if self.masks is None:
            self.compute_masks(params)
        return apply_masks(params, self.masks)


# ---------------------------------------------------------------------------
# distillation (slim/distillation/distiller.py parity)
# ---------------------------------------------------------------------------
def soft_label_distill_loss(student_logits, teacher_logits,
                            temperature=2.0):
    """SoftLabelDistiller: KL(teacher_T || student_T) * T^2 (Hinton)."""
    t = jax.nn.softmax(teacher_logits / temperature, axis=-1)
    log_s = jax.nn.log_softmax(student_logits / temperature, axis=-1)
    log_t = jax.nn.log_softmax(teacher_logits / temperature, axis=-1)
    kl = jnp.sum(t * (log_t - log_s), axis=-1)
    return jnp.mean(kl) * temperature ** 2


def l2_distill_loss(student_feat, teacher_feat):
    """L2Distiller: mean squared feature-map distance."""
    return jnp.mean((student_feat - teacher_feat) ** 2)


def fsp_matrix(a, b):
    """operators/fsp_op.cc parity — delegates to ops.misc.fsp_matrix
    (NCHW, like the rest of paddle_tpu.ops): [N,Ca,H,W] x [N,Cb,H,W]
    -> [N, Ca, Cb]."""
    from paddle_tpu.ops.misc import fsp_matrix as _fsp
    return _fsp(a, b)


def fsp_distill_loss(student_pair, teacher_pair):
    """FSPDistiller: L2 between student and teacher FSP matrices.
    Each pair is (feature_in, feature_out) from the same stage."""
    gs = fsp_matrix(*student_pair)
    gt = fsp_matrix(*teacher_pair)
    return jnp.mean((gs - gt) ** 2)
