"""Light-NAS: architecture search over token spaces.

Parity targets: python/paddle/fluid/contrib/slim/nas/
(light_nas_strategy.py LightNASStrategy, controller_server.py
ControllerServer, search_agent.py SearchAgent, search_space.py
SearchSpace) and slim/searcher/controller.py (EvolutionaryController,
SAController — simulated annealing over integer token vectors).

TPU-native shape: the controller/server/agent layer is plain host-side
C-like plumbing (a line-oriented text protocol, no pickle) and is kept
faithful; the per-candidate evaluation is where TPU idiom matters — a
candidate's `create_net(tokens)` returns jittable callables that train
through the normal trainer stack (DataParallelTrainer or a user loop),
so every candidate runs as one compiled XLA program.

Determinism: controllers take an explicit seed (the reference drew from
global numpy randomness, which made searches unreproducible).
"""

import logging
import math
import socket
import socketserver
import threading

import numpy as np

from paddle_tpu.core.enforce import enforce

__all__ = [
    "SearchSpace", "EvolutionaryController", "SAController",
    "ControllerServer", "SearchAgent", "LightNASStrategy",
]

_log = logging.getLogger("paddle_tpu.nas")


class SearchSpace:
    """Abstract token-space (ref nas/search_space.py).

    init_tokens() -> list<int>; range_table() -> list<int> with
    tokens[i] in [0, range_table[i]); create_net(tokens) -> whatever
    the evaluation callback consumes (idiomatically: a loss_fn +
    init_fn pair to hand to DataParallelTrainer)."""

    def init_tokens(self):
        raise NotImplementedError("Abstract method.")

    def range_table(self):
        raise NotImplementedError("Abstract method.")

    def create_net(self, tokens):
        raise NotImplementedError("Abstract method.")


class EvolutionaryController:
    """Abstract controller (ref searcher/controller.py)."""

    def update(self, tokens, reward):
        raise NotImplementedError("Abstract method.")

    def reset(self, range_table, init_tokens, constrain_func=None):
        raise NotImplementedError("Abstract method.")

    def next_tokens(self):
        raise NotImplementedError("Abstract method.")


class SAController(EvolutionaryController):
    """Simulated-annealing controller (ref searcher/controller.py
    SAController): accept a candidate when its reward improves, or with
    probability exp((reward - current)/T) under the decaying
    temperature T = init_temperature * reduce_rate**iter."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._rng = np.random.RandomState(seed)
        self._constrain_func = None
        self._reward = -float("inf")
        self._tokens = None
        self._max_reward = -float("inf")
        self._best_tokens = None
        self._iter = 0

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0
        # a fresh chain: stale rewards/bests from a previous search
        # would poison acceptance and report out-of-range tokens
        self._reward = -float("inf")
        self._max_reward = -float("inf")
        self._best_tokens = None

    def update(self, tokens, reward):
        self._iter += 1
        # floor keeps exp() well-defined when the geometric decay
        # underflows to 0.0 on very long (unbounded-server) searches
        temperature = max(self._init_temperature *
                          self._reduce_rate ** self._iter, 1e-300)
        if (reward > self._reward) or (self._rng.random_sample() <=
                                       math.exp(min((reward - self._reward)
                                                    / temperature, 0.0))):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)
        _log.info("iter %d: max_reward %s best %s", self._iter,
                  self._max_reward, self._best_tokens)

    def next_tokens(self):
        enforce(self._tokens is not None, "call reset() first")
        # mutate only dimensions with >1 choice (a size-1 range entry
        # is a fixed dimension; sampling it would both be pointless and
        # crash randint(0))
        movable = [i for i, r in enumerate(self._range_table) if r > 1]
        enforce(bool(movable),
                "search space has no dimension with more than one "
                "choice — nothing to search")
        tokens = list(self._tokens)
        new_tokens = list(tokens)
        index = movable[self._rng.randint(len(movable))]
        new_tokens[index] = (
            new_tokens[index]
            + self._rng.randint(self._range_table[index] - 1) + 1
        ) % self._range_table[index]
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_iter_number):
            if self._constrain_func(new_tokens):
                return new_tokens
            index = movable[self._rng.randint(len(movable))]
            new_tokens = list(tokens)
            new_tokens[index] = self._rng.randint(
                self._range_table[index])
        return new_tokens


# ---------------------------------------------------------------------------
# client/server loop (ref nas/controller_server.py + search_agent.py):
# line-oriented text protocol — "next_tokens\n" or
# "<key>\t<t0,t1,...>\t<reward>\n" -> "<t0,t1,...>\n". No pickle.
# ---------------------------------------------------------------------------
class ControllerServer:
    """Socket wrapper around a controller so distributed search agents
    (one per candidate-training job) share one annealing chain."""

    def __init__(self, controller, address=("127.0.0.1", 0),
                 max_client_num=100, search_steps=None, key="light-nas"):
        self._controller = controller
        self._address = address
        self._max_client_num = max_client_num   # listen backlog
        self._search_steps = search_steps
        self._key = key
        self._lock = threading.Lock()
        self._server = None
        self._thread = None

    def _exhausted(self):
        return (self._search_steps is not None
                and getattr(self._controller, "_iter", 0)
                >= self._search_steps)

    def start(self):
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                line = self.rfile.readline().decode("utf-8").strip()
                with outer._lock:
                    if line == "next_tokens":
                        toks = outer._controller.next_tokens()
                    else:
                        parts = line.split("\t")
                        if len(parts) < 3 or parts[0] != outer._key:
                            _log.info("noise from %s: %r",
                                      self.client_address, line[:80])
                            return
                        if outer._exhausted():
                            # search budget spent: stop accepting
                            # updates, serve the best tokens found
                            toks = (outer._controller.best_tokens
                                    or outer._controller.next_tokens())
                        else:
                            try:
                                tokens = [int(t)
                                          for t in parts[1].split(",")]
                                reward = float(parts[2])
                            except ValueError:
                                _log.warning(
                                    "malformed update from %s: %r",
                                    self.client_address, line[:80])
                                return
                            outer._controller.update(tokens, reward)
                            toks = outer._controller.next_tokens()
                self.wfile.write(
                    (",".join(str(t) for t in toks) + "\n").encode())

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            request_queue_size = self._max_client_num

        self._server = Server(self._address, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def ip(self):
        return self._server.server_address[0]

    def port(self):
        return self._server.server_address[1]

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class SearchAgent:
    """Client side (ref nas/search_agent.py): one per training job."""

    def __init__(self, server_ip, server_port, key="light-nas"):
        self.server_ip = server_ip
        self.server_port = server_port
        self._key = key

    def _roundtrip(self, msg):
        with socket.create_connection(
                (self.server_ip, self.server_port), timeout=30) as s:
            s.sendall((msg + "\n").encode("utf-8"))
            data = b""
            while not data.endswith(b"\n"):
                chunk = s.recv(4096)
                if not chunk:
                    break
                data += chunk
        text = data.decode("utf-8").strip()
        enforce(text, "controller server sent no tokens (bad key?)")
        return [int(t) for t in text.split(",")]

    def next_tokens(self):
        return self._roundtrip("next_tokens")

    def update(self, tokens, reward):
        return self._roundtrip(
            "{}\t{}\t{}".format(self._key,
                                ",".join(str(t) for t in tokens),
                                float(reward)))


class LightNASStrategy:
    """Search-loop orchestration (ref nas/light_nas_strategy.py,
    re-expressed functionally): every step asks the controller for
    tokens, builds the candidate via the SearchSpace, trains/evaluates
    it through ``eval_fn``, and feeds the reward back.

    eval_fn(net, tokens) -> float reward — `net` is whatever
    create_net returned (idiomatically a jittable train/eval pair run
    through the normal trainer stack). With ``agent`` set, tokens come
    from a remote ControllerServer so many hosts share one chain.
    """

    def __init__(self, search_space, controller=None, agent=None,
                 search_steps=50, constrain_func=None):
        enforce((controller is None) != (agent is None),
                "pass exactly one of controller= (in-process) or "
                "agent= (remote ControllerServer)")
        enforce(agent is None or constrain_func is None,
                "constrain_func cannot be enforced from agent mode — "
                "the chain lives on the ControllerServer; pass the "
                "constraint to the SERVER's controller.reset() instead")
        self.space = search_space
        self.controller = controller
        self.agent = agent
        self.search_steps = search_steps
        self.constrain_func = constrain_func

    def search(self, eval_fn):
        """Returns (best_tokens, best_reward, history)."""
        init = list(self.space.init_tokens())
        if self.controller is not None:
            self.controller.reset(self.space.range_table(), init,
                                  self.constrain_func)

        best_tokens, best_reward = init, -float("inf")
        history = []
        tokens = init
        for step in range(self.search_steps):
            net = self.space.create_net(tokens)
            reward = float(eval_fn(net, tokens))
            history.append((list(tokens), reward))
            if reward > best_reward:
                best_tokens, best_reward = list(tokens), reward
            if self.controller is not None:
                self.controller.update(tokens, reward)
                tokens = self.controller.next_tokens()
            else:
                tokens = self.agent.update(tokens, reward)
        return best_tokens, best_reward, history
