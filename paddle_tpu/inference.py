"""Inference stack: Config + Predictor over frozen programs.

Parity: the reference's inference/ side stack — AnalysisConfig
(inference/api/analysis_config.cc), AnalysisPredictor with ZeroCopyTensor
I/O (inference/api/analysis_predictor.h:46,56,68), the analysis pass
pipeline (inference/analysis/passes/passes.cc), and NaiveExecutor's
lock-free per-op loop (framework/naive_executor.cc).

TPU-native shape: a frozen program compiles AHEAD OF TIME into ONE XLA
computation per input-shape signature (the per-op NaiveExecutor loop and
the TRT subgraph engine both collapse into whole-program XLA); compiled
executables are cached per shape bucket, so serving at a handful of batch
sizes pays compilation once each. "Zero copy" here is jax.device_put
into the executable's donated input layout.
"""

import numpy as np

from paddle_tpu.core.place import CPUPlace
from paddle_tpu.static.executor import Executor, Scope
from paddle_tpu.static import io as static_io

__all__ = ["Config", "Predictor", "create_predictor", "ZeroCopyTensor"]


class Config:
    """AnalysisConfig parity (the knobs that are meaningful on TPU)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._ir_optim = True
        self._memory_optim = False
        self._device = None          # None → default backend

    def set_model(self, model_dir, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self):
        # XLA owns buffer reuse inside the compiled program — the
        # reference's memory_optimize pass is subsumed; kept as a no-op
        # toggle for API parity (inference/api/analysis_config.cc)
        self._memory_optim = True

    def disable_gpu(self):
        self._device = "cpu"

    def ir_optim(self):
        return self._ir_optim


class ZeroCopyTensor:
    """Input/output handle (AnalysisPredictor::GetInputTensor parity)."""

    def __init__(self, name, owner):
        self.name = name
        self._owner = owner

    def copy_from_cpu(self, arr):
        self._owner._feeds[self.name] = np.asarray(arr)

    def reshape(self, shape):  # parity no-op: shape comes from the array
        pass

    def copy_to_cpu(self):
        out = self._owner._outputs.get(self.name)
        if out is None:
            raise KeyError(f"output {self.name!r} not computed yet; run()")
        return np.asarray(out)




class Predictor:
    """AOT-compiled predictor over a save_inference_model artifact.

    One XLA executable per input-shape signature, cached — the analog of
    AnalysisPredictor's prepared scope + NaiveExecutor, with compilation
    replacing per-op dispatch.
    """

    def __init__(self, config):
        self.config = config
        self._scope = Scope()
        self._exe = Executor(CPUPlace())
        prog, feeds, fetches = static_io.load_inference_model(
            config.model_dir, self._exe,
            model_filename=config.prog_file,
            params_filename=config.params_file, scope=self._scope)
        if config.ir_optim():
            # re-prune to the fetch-reachable subgraph (idempotent on
            # save_inference_model artifacts, which prune at save; covers
            # hand-built or stale programs) — shares static/io's pass
            prog = static_io._prune(prog, feeds, fetches)
        self._program = prog
        self._feed_names = feeds
        self._fetch_names = fetches
        self._feeds = {}
        self._outputs = {}

    # -- introspection (AnalysisPredictor::GetInputNames parity) -----------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return ZeroCopyTensor(name, self)

    def get_output_handle(self, name):
        return ZeroCopyTensor(name, self)

    # -- execution ----------------------------------------------------------
    def run(self, feed=None):
        """feed: optional {name: array} (else use zero-copy handles).
        Returns outputs in fetch order. Compilation is cached per input
        shape signature by the Executor."""
        if feed is not None:
            self._feeds = {k: np.asarray(v) for k, v in feed.items()}
        missing = [n for n in self._feed_names if n not in self._feeds]
        if missing:
            raise KeyError(f"missing inputs: {missing}")
        outs = self._exe.run(self._program, feed=dict(self._feeds),
                             fetch_list=list(self._fetch_names),
                             scope=self._scope)
        self._outputs = dict(zip(self._fetch_names, outs))
        return outs


def create_predictor(config):
    """create_paddle_predictor / CreatePaddlePredictor parity."""
    return Predictor(config)
