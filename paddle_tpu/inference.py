"""Inference stack: Config + Predictor over frozen programs.

Parity: the reference's inference/ side stack — AnalysisConfig
(inference/api/analysis_config.cc), AnalysisPredictor with ZeroCopyTensor
I/O (inference/api/analysis_predictor.h:46,56,68), the analysis pass
pipeline (inference/analysis/passes/passes.cc), and NaiveExecutor's
lock-free per-op loop (framework/naive_executor.cc).

TPU-native shape: a frozen program compiles AHEAD OF TIME into ONE XLA
computation per input-shape signature (the per-op NaiveExecutor loop and
the TRT subgraph engine both collapse into whole-program XLA); compiled
executables are cached per shape bucket, so serving at a handful of batch
sizes pays compilation once each. "Zero copy" here is jax.device_put
into the executable's donated input layout.
"""

import hashlib
import json
import os
import threading
import time
import zlib

import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.place import CPUPlace
from paddle_tpu.static.executor import Executor, Scope, exec_op
from paddle_tpu.static import io as static_io

__all__ = ["Config", "Predictor", "create_predictor", "ZeroCopyTensor",
           "export_aot", "verify_aot_dir", "read_aot_version",
           "load_quantized_params", "AOTIntegrityError"]

AOT_DIR = "__aot__"
AOT_INDEX = "index.json"


def _build_pure_fn(program, feed_names, fetch_names):
    """A jittable fn(params_tuple, feeds_tuple) -> fetches_tuple over a
    frozen (host-op-free) inference program. Param/feed orders are the
    sorted state names / the given feed order — recorded in the AOT
    index so a loader binds buffers without re-reading the program."""
    import jax

    blk = program.global_block()
    ops = list(blk.ops)
    enforce(not any(op.attrs.get("_host") for op in ops),
            "AOT export requires a host-op-free inference program")
    constants = dict(getattr(program, "_constants", {}))
    state_names = sorted(n for n, v in blk.vars.items()
                         if v.persistable and n not in constants)
    seed = program.random_seed

    def fn(params, feeds):
        env = dict(constants)
        env.update(zip(state_names, params))
        env.update(zip(feed_names, feeds))
        key = None
        for i, op in enumerate(ops):
            if op.attrs.get("_needs_rng"):
                if key is None:
                    # match the Executor's derivation at its first run
                    # (fold_in(base, step_idx=0) then per-op index; no
                    # host ops here, so no index adjustment). Inference
                    # is stateless: every AOT call draws step-0 keys.
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(seed), 0)
                # an optimized program (opt_passes) pins each rng op's
                # pre-pass index in _rng_idx so masks match the
                # unoptimized lowering
                k = jax.random.fold_in(
                    key, op.attrs.get("_rng_idx", i))
            else:
                k = None
            env.update(exec_op(op, env, k))
        return tuple(env[n] for n in fetch_names)

    return fn, state_names


def _program_hash(program):
    """Fingerprint of the frozen program: AOT index entries are valid
    only for the exact graph they were compiled from. Canonical
    structural hash (static/serialize.py) — stable across
    interpreter/numpy versions, unlike the r2 pickle-bytes hash whose
    drift silently disabled the AOT fast path (ADVICE-r2)."""
    from paddle_tpu.static.serialize import program_fingerprint

    return program_fingerprint(program)[:16]


_XLA_MAGIC = b"PTXLA1"


def _aot_treedefs(n_params, n_feeds, n_out):
    """Rebuild the jit call's (in_tree, out_tree) from leaf counts —
    the fn signature is fn(params_tuple, feeds_tuple) -> outputs_tuple,
    so the tree-defs are fully determined by the counts and never need
    to be pickled into the artifact."""
    import jax

    in_tree = jax.tree.structure(
        ((tuple(range(n_params)), tuple(range(n_feeds))), {}))
    out_tree = jax.tree.structure(tuple(range(n_out)))
    return in_tree, out_tree


def _sig_of(feed_names, shaped):
    """Signature entry for one shape bucket: [[name, shape, dtype]...]
    in feed order. ``shaped``: {name: array-or-(shape, dtype)}."""
    sig = []
    for n in feed_names:
        v = shaped[n]
        if isinstance(v, tuple):
            shape, dtype = v
        else:
            shape, dtype = np.shape(v), np.asarray(v).dtype
        sig.append([n, [int(d) for d in shape], np.dtype(dtype).name])
    return sig


def _sig_key(sig):
    return hashlib.sha256(json.dumps(sig).encode()).hexdigest()[:16]


class AOTIntegrityError(RuntimeError):
    """An AOT artifact failed its integrity manifest (CRC/size drift or
    a missing file): positive evidence of a torn or bit-rotted export,
    named precisely — distinct from the silent degrade-to-retrace path
    taken for wrong-platform/wrong-version artifacts."""


class AOTVerifyResult(int):
    """``verify_aot_dir``'s return value: the number of artifact files
    verified (an int, so every existing ``== N`` caller keeps working)
    plus the ``model_version`` the manifest declares (``None`` for
    legacy/absent indexes). The version is what the serving hot-swap
    gate compares against the live server (docs/SERVING.md
    "Hot model swap")."""

    def __new__(cls, verified, model_version=None):
        self = super().__new__(cls, int(verified))
        self.model_version = model_version
        return self


def _model_version_of(prog_hash, state_names, params):
    """Deterministic content hash of (program, weights) plus an export
    timestamp: ``<sha256[:12]>.<unix-microseconds>``. Two exports of
    identical content get distinct versions (the timestamp is the
    publish event — a republish is a deliberate deploy signal for
    ``watch_dir`` mode), while the hash half answers "is this the same
    model bits" for operators reading logs."""
    h = hashlib.sha256(prog_hash.encode())
    for n, p in zip(state_names, params):
        h.update(n.encode())
        h.update(str(p.shape).encode())
        h.update(np.dtype(p.dtype).name.encode())
        h.update(np.ascontiguousarray(p).tobytes())
    return f"{h.hexdigest()[:12]}.{int(time.time() * 1e6)}"


def _file_integrity(path):
    """{"crc32", "nbytes"} of a file's byte image (the io_checkpoint
    idiom, applied to opaque artifact files)."""
    crc = 0
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            n += len(chunk)
    return {"crc32": crc & 0xFFFFFFFF, "nbytes": n}


def _verify_artifact(path, expect):
    """Verify one artifact file against its manifest record; raises
    :class:`AOTIntegrityError` naming the file and the first mismatch."""
    name = os.path.basename(path)
    try:
        got = _file_integrity(path)
    except FileNotFoundError:
        raise AOTIntegrityError(
            f"AOT artifact {name!r} is missing but listed in the "
            f"integrity manifest — torn export; re-run export_aot")
    if got["nbytes"] != expect["nbytes"]:
        raise AOTIntegrityError(
            f"AOT artifact {name!r} failed integrity: size "
            f"{got['nbytes']} != manifest {expect['nbytes']} — torn "
            f"export or concurrent rewrite; re-run export_aot")
    if got["crc32"] != expect["crc32"]:
        raise AOTIntegrityError(
            f"AOT artifact {name!r} failed integrity: CRC32 "
            f"{got['crc32']:#010x} != manifest "
            f"{expect['crc32']:#010x} — bit rot or torn export; "
            f"re-run export_aot")


def _version_from_entries(entries):
    """The manifest's model version: the NEWEST per-entry stamp by
    publish timestamp (the ``.<unix-micros>`` suffix). An index merged
    across exports keeps older entries with older stamps — the latest
    export is the dir's deploy identity."""
    best, best_ts = None, -1
    for e in entries if isinstance(entries, list) else []:
        if not isinstance(e, dict):
            continue
        v = e.get("model_version")
        if not v:
            continue
        try:
            ts = int(str(v).rsplit(".", 1)[1])
        except (IndexError, ValueError):
            ts = 0
        if ts >= best_ts:
            best, best_ts = v, ts
    return best


def verify_aot_dir(model_dir):
    """Verify every AOT artifact under ``<model_dir>/__aot__`` against
    the index's integrity manifest. Returns an :class:`AOTVerifyResult`
    — an int (the number of files verified; 0 when there is no AOT
    index, or for legacy indexes without integrity records — nothing to
    vouch for) carrying ``model_version`` (the manifest's declared
    version, or None); raises :class:`AOTIntegrityError` on the first
    bad file. The serving server runs this at warm boot AND at every
    hot-swap gate (``InferenceServer.swap``) so corruption fails at
    load/swap time, not mid-traffic."""
    aot_dir = os.path.join(model_dir or "", AOT_DIR)
    index_path = os.path.join(aot_dir, AOT_INDEX)
    if not os.path.exists(index_path):
        return AOTVerifyResult(0)
    try:
        with open(index_path) as f:
            entries = json.load(f)
    except (OSError, ValueError) as e:
        raise AOTIntegrityError(
            f"AOT index {index_path!r} is unreadable ({e}); re-run "
            f"export_aot")
    verified = 0
    for e in entries if isinstance(entries, list) else []:
        if not isinstance(e, dict):
            continue
        for name, rec in sorted(e.get("integrity", {}).items()):
            _verify_artifact(os.path.join(aot_dir, name), rec)
            verified += 1
    return AOTVerifyResult(verified, _version_from_entries(entries))


def load_quantized_params(model_dir):
    """The quantized-serving sidecar of ``export_aot(quantize=...)``,
    or None when the dir has no quantized export. Returns
    ``{"mode", "weights", "values"}`` where ``values`` maps each
    quantized weight (and its ``@quant_scale`` table for int8) to the
    stored array. The sidecar's CRC is part of the integrity manifest —
    run ``verify_aot_dir`` first (the serving boot/swap gate does);
    this loader re-checks the file against the newest entry's record
    so a direct caller can't load tampered scales either. The WEIGHT
    LIST comes from the manifest, never re-derived — the loader applies
    exactly what the exporter quantized (static/opt_passes.
    apply_weight_quant refuses on mismatch)."""
    index_path = os.path.join(model_dir or "", AOT_DIR, AOT_INDEX)
    try:
        with open(index_path) as f:
            entries = json.load(f)
    except (OSError, ValueError):
        return None
    # the NEWEST export overall decides, not the newest export that
    # happens to carry a quant block: a later fp32 re-export under a
    # different shape-bucket set leaves older entries in the index
    # (key-based pruning), and serving its stale sidecar would
    # silently overwrite the freshly loaded fp32 weights
    best, best_ts = None, -1
    for e in entries if isinstance(entries, list) else []:
        if not isinstance(e, dict):
            continue
        v = e.get("model_version")
        try:
            ts = int(str(v).rsplit(".", 1)[1])
        except (IndexError, ValueError, AttributeError):
            ts = 0
        if ts > best_ts or (
                ts == best_ts
                and isinstance(e.get("quant"), dict)
                and not isinstance((best or {}).get("quant"), dict)):
            best, best_ts = e, ts
    if best is None or not isinstance(best.get("quant"), dict):
        return None
    q = best["quant"]
    qpath = os.path.join(model_dir, AOT_DIR, q.get("file", ""))
    rec = (best.get("integrity") or {}).get(q.get("file"))
    if not rec:
        # quant sidecars have carried integrity records since the
        # feature shipped — an entry without one is a doctored index,
        # not a legacy artifact; refusing beats loading unverifiable
        # scale tables
        raise AOTIntegrityError(
            f"quantized sidecar {q.get('file')!r} has no integrity "
            f"record in the AOT index; treating as tampered — re-run "
            f"export_aot")
    _verify_artifact(qpath, rec)
    try:
        with np.load(qpath) as z:
            values = {k: z[k] for k in z.files}
    except (OSError, ValueError) as e:
        raise AOTIntegrityError(
            f"quantized sidecar {qpath!r} is unreadable ({e}); "
            f"re-run export_aot")
    mode = q.get("mode")
    weights = list(q.get("weights", []))
    if mode == "bf16":
        import jax.numpy as jnp
        values = {k: (v.view(jnp.bfloat16) if k in weights else v)
                  for k, v in values.items()}
    return {"mode": mode, "weights": weights, "values": values}


def read_aot_version(model_dir):
    """The manifest's ``model_version`` WITHOUT verifying artifact
    CRCs — a cheap index-only probe (one small JSON read) for the
    hot-swap directory watcher, which polls it every interval; the
    full CRC pass runs once, at the swap gate. Returns None when the
    dir has no AOT index, the index is unreadable, or the export
    predates versioning."""
    index_path = os.path.join(model_dir or "", AOT_DIR, AOT_INDEX)
    try:
        with open(index_path) as f:
            return _version_from_entries(json.load(f))
    except (OSError, ValueError):
        return None


def export_aot(dirname, program, feed_names, fetch_names, scope,
               shape_buckets, platforms=("cpu", "tpu"), quantize=None,
               apply_passes=None):
    """Compile the frozen program per shape bucket and serialize BOTH
    artifacts (the VERDICT-r1 'inference artifact export' gap; ref
    capability: inference/io.cc + analysis_predictor.h:46 serialize an
    optimized deployable model):

    - <h>.xla — the platform-native compiled executable
      (jax.experimental.serialize_executable): loading skips tracing
      AND XLA compilation, but pins platform + jax version;
    - <h>.shlo — portable StableHLO (jax.export): loading skips Python
      retracing/program analysis; XLA compiles once at load.

    ``shape_buckets``: list of {feed name: (shape, dtype)} (or example
    arrays). ``platforms`` lowers the portable export for each named
    platform (default cpu+tpu) so the .shlo artifact really is
    cross-platform. Returns the index entries.

    ``apply_passes`` (default: ``FLAGS_apply_ir_passes``) runs the
    program-level optimization pipeline (static/opt_passes.py) on a
    clone of the frozen program before compiling.

    ``quantize="int8"|"bf16"`` additionally performs weight-only
    post-training quantization (docs/SERVING.md "Quantized serving"):
    every eligible matmul weight is stored quantized (int8: per-output-
    channel abs-max scales; bf16: storage cast) in a ``quant.<mode>.npz``
    sidecar under ``__aot__`` — covered by the integrity manifest, so
    a tampered scale table fails ``verify_aot_dir`` — and the dequant
    is folded into the consuming matmul as one ``fused_matmul`` op.
    The serving warm boot (``InferenceServer``/``swap``) loads such a
    dir transparently with int8-resident params; the single-request
    ``Predictor`` keeps using the fp32 params file."""
    import jax
    import jax.export  # not in the jax namespace by default on this pin
    from jax.experimental import serialize_executable as se

    from paddle_tpu.core.flags import get_flag
    from paddle_tpu.static import opt_passes as _opt

    if apply_passes is None:
        apply_passes = bool(get_flag("apply_ir_passes"))
    # the deploy identity is the CALLER's program — the same graph
    # save_inference_model wrote. The Predictor matches entries by the
    # hash of the loaded __model__, which never sees the pass/quant
    # rewrites below, so hashing the rewritten clone would orphan
    # every entry into the silent retrace path.
    prog_hash = _program_hash(program)
    if apply_passes:
        program = _opt.optimize_inference(program, fetch_names)
    out_dir = os.path.join(dirname, AOT_DIR)
    os.makedirs(out_dir, exist_ok=True)
    overlay = {}
    qmeta = None
    if quantize is not None:
        enforce(quantize in ("int8", "bf16"),
                f"quantize must be 'int8' or 'bf16', got {quantize!r}")
        blk = program.global_block()
        values = {n: np.asarray(scope.find_var(n))
                  for n, v in blk.vars.items()
                  if getattr(v, "persistable", False)
                  and scope.find_var(n) is not None}
        plan = _opt.plan_weight_quant(program, values, quantize)
        enforce(plan,
                f"quantize={quantize!r}: no eligible weight found "
                f"(2-D persistable float32 consumed only as a "
                f"matmul/mul RHS in [in, out] layout)")
        program = _opt.apply_weight_quant(program, plan, quantize)
        overlay = _opt.quantize_weight_values(values, plan, quantize)
        # per-export filename (the {h}.xla idiom): a FIXED name would
        # let a later quantized re-export overwrite the file older
        # surviving index entries still record CRCs for (npz bytes are
        # not reproducible — zip headers embed mtimes), and
        # verify_aot_dir would then refuse the whole dir after a
        # legitimate export. Dropped entries' sidecars are unlinked by
        # the live_files sweep below.
        qfile = f"quant.{quantize}.{time.time_ns() // 1000}.npz"
        qtmp = os.path.join(out_dir, f".{qfile}.{os.getpid()}.tmp")
        with open(qtmp, "wb") as f:
            # bf16 has no stable npz dtype (numpy reloads it as void):
            # store the raw 16-bit lanes; the loader views them back
            np.savez(f, **{
                k: (np.asarray(v).view(np.uint16)
                    if quantize == "bf16" and k in plan else v)
                for k, v in overlay.items()})
        os.replace(qtmp, os.path.join(out_dir, qfile))
        qmeta = {
            "mode": quantize, "file": qfile, "weights": sorted(plan),
            # per-weight scale-table digests: the manifest names the
            # exact scale bytes a loader must see (the file CRC in
            # `integrity` is the enforcement; this is the evidence an
            # operator can diff across exports)
            "scales_sha256": {
                w: hashlib.sha256(np.ascontiguousarray(
                    overlay[w + _opt.QUANT_SCALE_SUFFIX])
                    .tobytes()).hexdigest()[:16]
                for w in plan} if quantize == "int8" else {},
        }

    fn, state_names = _build_pure_fn(program, feed_names, fetch_names)
    raw = [overlay.get(n, scope.find_var(n)) for n in state_names]
    missing = [n for n, v in zip(state_names, raw) if v is None]
    enforce(not missing,
            f"scope missing persistables for AOT export: {missing[:5]}")
    params = tuple(np.asarray(v) for v in raw)
    param_sds = tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                      for p in params)
    jitted = jax.jit(fn)
    entries = []
    platform = jax.devices()[0].platform
    # the deploy identity of THIS export (content hash + publish
    # timestamp), stamped on every entry — the serving hot-swap
    # gate/watcher reads the newest stamp back via
    # verify_aot_dir/read_aot_version
    model_version = _model_version_of(prog_hash, state_names, params)
    for bucket in shape_buckets:
        sig = _sig_of(feed_names, bucket)
        feed_sds = tuple(
            jax.ShapeDtypeStruct(tuple(s), np.dtype(dt))
            for _, s, dt in sig)
        # the key covers the PROGRAM too: a re-saved model must never
        # serve a stale graph from a surviving shape bucket
        h = _sig_key(sig + [["__program__", [], prog_hash]])
        compiled = jitted.lower(param_sds, feed_sds).compile()
        try:
            # compile-time memory ledger: each bucket's footprint is
            # a capacity-planning number the swap admission and the
            # postmortems read back (monitor/memory.py)
            from paddle_tpu.monitor import memory as _memory
            _memory.record_segment_memory(
                ("export", prog_hash), bucket,
                _memory.analyze_compiled(compiled))
        except Exception:
            pass
        # the unsharded jit above compiles single-device; recorded so
        # the loader binds the executable to exactly that many devices
        entry = {"sig": sig, "key": h, "platform": platform,
                 "jax_version": jax.__version__,
                 "program_hash": prog_hash,
                 "model_version": model_version,
                 "state_names": state_names, "num_devices": 1}
        payload, in_tree, out_tree = se.serialize(compiled)
        # the wrapper is a structural container (header + counts +
        # payload), NOT a pickle: tree-defs are rebuilt from counts at
        # load. The payload itself is jax's serialize_executable blob —
        # deserializing it is jax's trust boundary (see Predictor docs).
        expect_in, expect_out = _aot_treedefs(
            len(param_sds), len(feed_sds), len(fetch_names))
        enforce(expect_in == in_tree and expect_out == out_tree,
                "AOT treedef layout drifted from (params, feeds) -> "
                "outputs tuples; container format needs updating")
        meta = json.dumps({"n_params": len(param_sds),
                           "n_feeds": len(feed_sds),
                           "n_out": len(fetch_names)}).encode("utf-8")
        with open(os.path.join(out_dir, f"{h}.xla"), "wb") as f:
            f.write(_XLA_MAGIC + len(meta).to_bytes(4, "little")
                    + meta + payload)
        entry["xla"] = f"{h}.xla"
        exported = jax.export.export(jitted,
                                     platforms=list(platforms))(
            param_sds, feed_sds)
        with open(os.path.join(out_dir, f"{h}.shlo"), "wb") as f:
            f.write(exported.serialize())
        entry["shlo"] = f"{h}.shlo"
        if qmeta is not None:
            entry["quant"] = qmeta
        # integrity manifest (the PR-5 checkpoint idiom, for opaque
        # artifact files): CRC32 + size per artifact, verified at
        # Predictor/server load so a torn export names its first bad
        # file instead of surfacing as a raw deserialization traceback
        # — the quant sidecar (weights + scale tables) is covered too,
        # so a quantized artifact is tamper-evident end to end
        entry["integrity"] = {
            name: _file_integrity(os.path.join(out_dir, name))
            for name in ([entry["xla"], entry["shlo"]]
                         + ([qmeta["file"]] if qmeta else []))}
        entries.append(entry)
    index_path = os.path.join(out_dir, AOT_INDEX)
    existing = []
    old = []
    if os.path.exists(index_path):
        try:
            with open(index_path) as f:
                old = json.load(f)
            if not isinstance(old, list):
                old = []
            old = [e for e in old
                   if isinstance(e, dict) and "key" in e]
        except (OSError, ValueError):
            # corrupt index from an interrupted export: re-exporting
            # must self-heal (we lose only this run's stale-artifact
            # GC), not crash on the recovery path
            old = []
    if old:
        # drop superseded buckets AND any entry for a different
        # (stale) program — and unlink their artifact files, or a
        # periodically re-exported serving dir grows without bound
        keep, dropped = [], []
        new_keys = {x["key"] for x in entries}
        for e in old:
            if (e["key"] not in new_keys
                    and e.get("program_hash") == prog_hash):
                keep.append(e)
            else:
                dropped.append(e)
        existing = keep
        # a dropped entry's quant sidecar is shared by every entry of
        # its export — unlink only when no surviving entry references it
        live_files = {n for e in keep + entries
                      for n in (e.get("xla"), e.get("shlo"),
                                (e.get("quant") or {}).get("file"))
                      if n}
        for e in dropped:
            # the sidecar is uniquely named per export, so a same-key
            # re-export does NOT rewrite it in place the way {h}.xla /
            # {h}.shlo are rewritten — the dropped entry's old sidecar
            # must be swept here or a continuous-deploy loop leaks one
            # full-weight npz per publish
            old_q = (e.get("quant") or {}).get("file")
            if old_q and old_q not in live_files:
                try:
                    os.unlink(os.path.join(out_dir, old_q))
                except OSError:
                    pass
            if e["key"] in new_keys:
                continue   # same key: this export just rewrote the files
            for name in (e.get("xla"), e.get("shlo")):
                if name and name not in live_files:
                    try:
                        os.unlink(os.path.join(out_dir, name))
                    except OSError:
                        pass
    # atomic replace: a reader (or a killed exporter) must never see a
    # truncated index. The dir-level model_version is the NEWEST
    # per-entry stamp (kept entries from older exports carry older
    # ones) — the index stays a plain list of bucket entries.
    tmp = f"{index_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(existing + entries, f, indent=1)
    os.replace(tmp, index_path)
    return entries


class Config:
    """AnalysisConfig parity (the knobs that are meaningful on TPU)."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._ir_optim = True
        self._memory_optim = False
        self._device = None          # None → default backend

    def set_model(self, model_dir, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self):
        # XLA owns buffer reuse inside the compiled program — the
        # reference's memory_optimize pass is subsumed; kept as a no-op
        # toggle for API parity (inference/api/analysis_config.cc)
        self._memory_optim = True

    def disable_gpu(self):
        self._device = "cpu"

    def ir_optim(self):
        return self._ir_optim


class ZeroCopyTensor:
    """Input/output handle (AnalysisPredictor::GetInputTensor parity)."""

    def __init__(self, name, owner):
        self.name = name
        self._owner = owner

    def copy_from_cpu(self, arr):
        self._owner._feeds[self.name] = np.asarray(arr)

    def reshape(self, shape):  # parity no-op: shape comes from the array
        pass

    def copy_to_cpu(self):
        out = self._owner._outputs.get(self.name)
        if out is None:
            raise KeyError(f"output {self.name!r} not computed yet; run()")
        return np.asarray(out)




class Predictor:
    """AOT-compiled predictor over a save_inference_model artifact.

    One XLA executable per input-shape signature, cached — the analog of
    AnalysisPredictor's prepared scope + NaiveExecutor, with compilation
    replacing per-op dispatch.

    Trust boundary: the model dir's program (__model__, schema'd JSON)
    and params (.npz) load without executing code. The optional AOT
    fast-path artifacts are different: the portable ``.shlo`` file is
    plain StableHLO, but the platform-native ``.xla`` payload is
    deserialized by jax.experimental.serialize_executable, which
    unpickles internally — load ``.xla`` artifacts only from model
    directories you trust as much as the code itself (our wrapper
    container is structural, the pickle is jax's own layer).

    Thread safety: ``run(feed=...)`` is serialized by a per-predictor
    lock — concurrent callers on ONE predictor get correct (if
    convoyed) results instead of corrupting each other's
    ``_feeds``/``_outputs`` handle state. The SCALING contract is still
    ``clone()``-per-thread (shared weights/executables, private handle
    state, no lock contention); the zero-copy handle flow
    (``get_input_handle`` → ``copy_from_cpu`` → ``run()`` →
    ``copy_to_cpu``) spans multiple calls and is only safe on a
    predictor the thread owns — use clones there. For real QPS use
    ``paddle_tpu.serving.InferenceServer`` (docs/SERVING.md).
    """

    def __init__(self, config):
        self.config = config
        self._run_lock = threading.Lock()
        self._scope = Scope()
        self._exe = Executor(CPUPlace())
        prog, feeds, fetches = static_io.load_inference_model(
            config.model_dir, self._exe,
            model_filename=config.prog_file,
            params_filename=config.params_file, scope=self._scope)
        # AOT index present? Only then hash the program AS SAVED
        # (before any local re-prune — the index was written against
        # exactly that graph); the structural hash walks the whole
        # program, so skip it for the common artifact without AOT
        # exports
        self._aot_idx_path = os.path.join(
            config.model_dir or "", AOT_DIR, AOT_INDEX)
        loaded_hash = (_program_hash(prog)
                       if config.model_dir
                       and os.path.exists(self._aot_idx_path) else None)
        if config.ir_optim():
            # re-prune to the fetch-reachable subgraph (idempotent on
            # save_inference_model artifacts, which prune at save; covers
            # hand-built or stale programs) — shares static/io's pass
            prog = static_io._prune(prog, feeds, fetches)
        self._program = prog
        self._feed_names = feeds
        self._fetch_names = fetches
        self._feeds = {}
        self._outputs = {}
        # AOT artifacts (export_aot): signature key -> index entry;
        # loaded (callable, params) cache per key. Entries for a
        # different program hash are ignored — stale artifacts must
        # never serve an old graph.
        self._aot_index = {}
        self._aot_loaded = {}
        self._prog_hash = loaded_hash
        if loaded_hash is not None:
            try:
                with open(self._aot_idx_path) as f:
                    for e in json.load(f):
                        if e.get("program_hash") == self._prog_hash:
                            self._aot_index[e["key"]] = e
            except Exception:
                # corrupt/unreadable/wrong-shape index: the
                # model+params are fine — degrade to the retrace path
                # like any other AOT artifact failure
                self._aot_index = {}

    # -- AOT path ----------------------------------------------------------
    def _aot_fn(self, feeds):
        """Return a loaded AOT callable for this feed signature, or
        None. Prefers the platform-native executable (no retrace, no
        compile); falls back to the portable StableHLO export (no
        retrace; XLA compiles once); returns None when neither loads
        (wrong platform/version) so the caller re-traces."""
        if not self._aot_index:
            return None
        sig = _sig_of(self._feed_names,
                      {n: feeds[n] for n in self._feed_names})
        h = _sig_key(sig + [["__program__", [], self._prog_hash]])
        if h in self._aot_loaded:
            return self._aot_loaded[h]
        entry = self._aot_index.get(h)
        if entry is None:
            # no negative caching: the probe is one sha256 over the
            # signature, and dynamic shapes would grow the cache
            # unboundedly in a long-lived server
            return None
        import jax
        import jax.export  # not in the jax namespace by default here

        aot_dir = os.path.join(self.config.model_dir, AOT_DIR)
        fn = None
        params = None
        if entry.get("quant"):
            # quantized entries expect int8/bf16 state this fp32
            # Predictor doesn't hold (scale tables live in the sidecar;
            # bf16 weights differ in dtype from the params file) — the
            # single-request path serves fp32 via retrace; the
            # integrity gate below still runs
            params = None
        else:
            try:
                # per-entry params (state_names may differ across
                # entries); any failure — e.g. a stale entry naming a
                # var the scope no longer holds — degrades to the
                # retrace path
                raw = [self._scope.find_var(n)
                       for n in entry["state_names"]]
                if not any(v is None for v in raw):
                    params = tuple(jax.device_put(np.asarray(v))
                                   for v in raw)
            except Exception:
                params = None
        # integrity gate BEFORE any deserialization attempt: CRC/size
        # drift is positive corruption evidence and raises precisely
        # (AOTIntegrityError names the file) — it must NOT be swallowed
        # into the degrade-to-retrace path reserved for wrong
        # platform/version artifacts
        integ = entry.get("integrity", {})
        for name in (entry.get("xla"), entry.get("shlo")):
            if name and name in integ:
                _verify_artifact(os.path.join(aot_dir, name),
                                 integ[name])
        if (params is not None and entry.get("xla")
                and entry["platform"] == jax.devices()[0].platform
                and entry["jax_version"] == jax.__version__):
            try:
                from jax.experimental import serialize_executable as se
                with open(os.path.join(aot_dir, entry["xla"]),
                          "rb") as f:
                    blob = f.read()
                if not blob.startswith(_XLA_MAGIC):
                    raise ValueError("bad .xla container magic")
                off = len(_XLA_MAGIC)
                hlen = int.from_bytes(blob[off:off + 4], "little")
                meta = json.loads(
                    blob[off + 4:off + 4 + hlen].decode("utf-8"))
                payload = blob[off + 4 + hlen:]
                in_tree, out_tree = _aot_treedefs(
                    meta["n_params"], meta["n_feeds"], meta["n_out"])
                fn = se.deserialize_and_load(
                    payload, in_tree, out_tree,
                    execution_devices=jax.devices()[
                        :entry.get("num_devices", 1)])
            except Exception:
                fn = None
        if params is not None and fn is None and entry.get("shlo"):
            try:
                with open(os.path.join(aot_dir, entry["shlo"]),
                          "rb") as f:
                    exported = jax.export.deserialize(f.read())
                # jit the exported call: compile once, then cached —
                # eager exported.call re-traces per request
                fn = jax.jit(exported.call)
            except Exception:
                fn = None
        loaded = None if fn is None else (fn, params)
        self._aot_loaded[h] = loaded
        return loaded

    # -- multi-thread serving (AnalysisPredictor::Clone parity) ------------
    def clone(self):
        """A predictor sharing this one's loaded weights, program,
        executor compile cache and AOT executables, but owning its
        per-request feed/fetch state — the multi-thread serving
        contract (ref: inference/api/analysis_predictor.h:46 Clone:
        'Create a new predictor sharing the weights'). One clone per
        serving thread; run() on different clones is concurrency-safe
        because the shared pieces are read-only after load and XLA
        executable invocation is thread-safe, while the mutable
        request state (_feeds/_outputs and the zero-copy handles bound
        to them) is per-clone."""
        c = object.__new__(Predictor)
        c.__dict__.update(self.__dict__)
        c._feeds = {}
        c._outputs = {}
        c._run_lock = threading.Lock()   # per-clone: clones must not
        return c                         # convoy on the parent's lock

    # -- introspection (AnalysisPredictor::GetInputNames parity) -----------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return ZeroCopyTensor(name, self)

    def get_output_handle(self, name):
        return ZeroCopyTensor(name, self)

    # -- execution ----------------------------------------------------------
    def run(self, feed=None):
        """feed: optional {name: array} (else use zero-copy handles).
        Returns outputs in fetch order. Compilation is cached per input
        shape signature by the Executor. Serialized by the predictor's
        lock: concurrent ``run(feed=...)`` calls on one predictor are
        safe (see the class docstring for the clone-per-thread scaling
        contract)."""
        with self._run_lock:
            if feed is not None:
                self._feeds = {k: np.asarray(v) for k, v in feed.items()}
            missing = [n for n in self._feed_names
                       if n not in self._feeds]
            if missing:
                raise KeyError(f"missing inputs: {missing}")
            aot = self._aot_fn(self._feeds)
            if aot is not None:
                fn, params = aot
                outs = fn(params,
                          tuple(self._feeds[n]
                                for n in self._feed_names))
                outs = [np.asarray(o) for o in outs]
            else:
                outs = self._exe.run(self._program,
                                     feed=dict(self._feeds),
                                     fetch_list=list(self._fetch_names),
                                     scope=self._scope)
            self._outputs = dict(zip(self._fetch_names, outs))
            return outs


def create_predictor(config):
    """create_paddle_predictor / CreatePaddlePredictor parity."""
    return Predictor(config)
