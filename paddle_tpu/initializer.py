"""Parameter initializers.

Parity target: python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArrayInitializer).
An initializer is a callable ``(key, shape, dtype) -> array``; in the
static path it becomes an op in the startup program (the reference runs
initializer ops there too).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Initializer", "Constant", "ConstantInitializer", "Uniform",
    "UniformInitializer", "Normal", "NormalInitializer", "TruncatedNormal",
    "TruncatedNormalInitializer", "Xavier", "XavierInitializer", "MSRA",
    "MSRAInitializer", "Bilinear", "BilinearInitializer",
    "NumpyArrayInitializer",
]


def _fans(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    # fluid convention: fan_in = shape[0]*receptive for conv (IOHW view is
    # [out,in,h,w]); for 2-D [in, out]
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, key, shape, dtype=jnp.float32):
        if self.seed:
            key = jax.random.PRNGKey(self.seed)
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, key, shape, dtype=jnp.float32):
        if self.seed:
            key = jax.random.PRNGKey(self.seed)
        return self.loc + self.scale * jax.random.normal(key, shape, dtype)


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, key, shape, dtype=jnp.float32):
        if self.seed:
            key = jax.random.PRNGKey(self.seed)
        return self.loc + self.scale * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype)


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, key, shape, dtype=jnp.float32):
        if self.seed:
            key = jax.random.PRNGKey(self.seed)
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return jax.random.uniform(key, shape, dtype, -limit, limit)
        std = math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, key, shape, dtype=jnp.float32):
        if self.seed:
            key = jax.random.PRNGKey(self.seed)
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return jax.random.uniform(key, shape, dtype, -limit, limit)
        std = math.sqrt(2.0 / fi)
        return std * jax.random.normal(key, shape, dtype)


class BilinearInitializer(Initializer):
    """For upsampling deconv filters (initializer.py Bilinear)."""

    def __call__(self, key, shape, dtype=jnp.float32):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs 4-D weight")
        f = np.zeros(shape, np.float32)
        k = shape[3]
        factor = (k + 1) // 2
        center = factor - 1.0 if k % 2 == 1 else factor - 0.5
        og = np.ogrid[:k, :k]
        filt = (1 - abs(og[0] - center) / factor) * \
               (1 - abs(og[1] - center) / factor)
        f[range(shape[0]), range(shape[1]) if shape[1] == shape[0] else 0] = filt
        return jnp.asarray(f, dtype)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.asarray(self.value, dtype).reshape(shape)


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


# force_init_on_cpu / init_on_cpu (ref python/paddle/fluid/initializer.py):
# on TPU, XLA owns initial placement — the flag is kept for API parity and
# honored by host-side consumers that check it (dataio staging).
_force_init_on_cpu_ = False


def force_init_on_cpu():
    return _force_init_on_cpu_


class _InitOnCPU:
    def __enter__(self):
        global _force_init_on_cpu_
        self._prev = _force_init_on_cpu_
        _force_init_on_cpu_ = True

    def __exit__(self, *a):
        global _force_init_on_cpu_
        _force_init_on_cpu_ = self._prev


def init_on_cpu():
    """Context manager: initializers inside run on host (parity shim)."""
    return _InitOnCPU()
