"""Program-level optimization pass library + the default pipeline.

Parity: the reference's ``framework/ir`` layer — ~70 registered graph
passes (fusion: fc_fuse_pass/conv_bn_fuse_pass, constant folding:
constant_folding_pass, pruning: graph_to_program_pass + prune.cc,
layout: transpose_flatten_concat_fuse_pass) applied by ParallelExecutor
and the inference AnalysisPredictor before execution (PAPER.md layer
map). Here the Program IS the IR (static/passes.py), so each pass is a
``ProgramPass`` over the op list, orchestrated by ``PassManager`` and
run by the Executor's compile path / ``export_aot`` behind
``BuildStrategy.apply_ir_passes`` / ``FLAGS_apply_ir_passes``
(docs/PERFORMANCE.md "Program pass pipeline").

Design constraints every pass obeys:

- **Never mutate the caller's program.** The drivers
  (``optimize_for_execution`` / ``optimize_inference``) clone first;
  the original object stays bit-identical for the
  ``apply_ir_passes=False`` A/B path.
- **RNG stability.** Removing/fusing ops shifts op indices, and the
  executor folds each rng op's key by its index — so the drivers stamp
  every ``_needs_rng`` op with ``_rng_idx`` (its pre-pass net index)
  and the executor/pure-fn honor it. Optimized and legacy programs
  draw IDENTICAL dropout masks (the equivalence fuzz pins exactness
  through rng ops).
- **Conservatism beats coverage.** A rewrite fires only when the
  matched vars are written once, the cancelled intermediates have no
  other consumer and are neither fetched nor persistable, and the
  chain doesn't cross a host-op/autodiff barrier. Anything uncertain
  is left alone — a skipped fusion costs nothing (XLA fuses anyway);
  a wrong one is a miscompile.

Evidence: every pass application publishes
``program_pass_runs_total{pass}`` / ``program_pass_ops_removed_total``
/ ``program_pass_ms`` through ``monitor/cost.py`` (``record_pass``),
and ``tools/dump_program.py --diff-passes`` prints the per-pass op
diff for triaging a miscompile to the guilty pass.

The weight-only PTQ half (``plan_weight_quant`` / ``apply_weight_quant``
/ ``quantize_weight_values``) serves ``export_aot(quantize=)`` and the
serving warm boot: per-channel abs-max int8 (or bf16 storage), with the
dequant folded into the consuming matmul as ONE ``fused_matmul`` op so
XLA sees convert+scale+dot as a single fusion (docs/SERVING.md
"Quantized serving").
"""

import time

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, enforce
from paddle_tpu.static.passes import PassManager, ProgramPass
from paddle_tpu.static.program import register_op

__all__ = [
    "ConstantFoldingPass", "FoldScaleCastChainPass",
    "CancelTransposeReshapePass", "FuseMatmulBiasActPass",
    "DeadOpEliminationPass", "default_pipeline", "optimize_program",
    "optimize_for_execution", "optimize_inference", "PipelineReport",
    "FUSED_MATMUL", "QUANT_SCALE_SUFFIX", "QUANT_BINS",
    "plan_weight_quant", "apply_weight_quant", "quantize_weight_values",
]

#: the fused matmul(+dequant)(+bias)(+act) op the fusion and quant
#: passes emit — semantics are BY CONSTRUCTION the composition of the
#: registered float ops it replaces (the compute calls them in
#: sequence), so fused == unfused bit-for-bit on the same backend
FUSED_MATMUL = "fused_matmul"
#: per-channel scale table var name: ``<weight>@quant_scale``
QUANT_SCALE_SUFFIX = "@quant_scale"
#: int8 bins (ops/quantize._bin_cnt(8)): q = round(w / scale * 127)
QUANT_BINS = 127

#: ops kept regardless of reachability (observable side effects that
#: don't ride the _host attr)
_SIDE_EFFECT_TYPES = frozenset({
    "print", "py_func", "save_combine", "load_combine",
    "ps_send", "ps_recv",
})

#: activations the matmul fusion absorbs (attr-free unary ops)
_FUSABLE_ACTS = frozenset({"relu", "sigmoid", "tanh", "gelu"})

_MATMUL_TYPES = ("mul", "matmul")


# ---------------------------------------------------------------------------
# fused op compute
# ---------------------------------------------------------------------------
def _fused_matmul_compute(ins, attrs):
    """x @ dequant(w) (+ bias) (+ act): the exact composition of the
    registered float ops (ops/math.mul|matmul, elementwise_add,
    activation) — XLA fuses convert/scale/dot/add/act into one kernel
    (the MXU path), the program sees ONE op.

    When the Pallas kernel registry selects a Pallas body
    (ops/pallas/registry.py), the fp and int8 variants run as single
    blocked kernels instead — dequant/bias/act fused into the tile loop.
    ``try_fused_matmul`` returns None for stock selection or operand
    patterns outside the kernel contract, keeping this flag-off path
    bit-identical."""
    import jax.numpy as jnp

    from paddle_tpu.ops import math as _m
    from paddle_tpu.ops.pallas import try_fused_matmul

    fast = try_fused_matmul(ins, attrs)
    if fast is not None:
        return {"Out": [fast]}
    xs = list(ins["X"])
    x, w = xs[0], xs[1]
    i = 2
    quant = attrs.get("quant")
    if quant == "int8":
        scale = xs[i]
        i += 1
        # weight-only dequant: per-output-channel abs-max scale over
        # the LAST axis of the [in, out] weight (broadcasts [out])
        w = w.astype(jnp.float32) * (scale / float(QUANT_BINS))
    elif quant == "bf16":
        w = w.astype(jnp.float32)
    out = getattr(_m, attrs["mm_type"])(x, w, **attrs.get("mm_attrs", {}))
    if attrs.get("has_bias"):
        out = _m.elementwise_add(out, xs[i],
                                 axis=attrs.get("bias_axis", -1))
        i += 1
    act = attrs.get("act")
    if act:
        from paddle_tpu import ops as _ops
        out = getattr(_ops, act)(out)
    return {"Out": [out]}


register_op(FUSED_MATMUL, _fused_matmul_compute)


# ---------------------------------------------------------------------------
# shared analysis helpers
# ---------------------------------------------------------------------------
def _block(program):
    return program.global_block()


def _write_counts(block):
    c = {}
    for op in block.ops:
        for n in op.output_names():
            c[n] = c.get(n, 0) + 1
    return c


def _consumer_map(block):
    out = {}
    for i, op in enumerate(block.ops):
        for n in set(op.input_names()):
            out.setdefault(n, []).append((i, op))
    return out


def _write_indices(block):
    """{name: [op indices that write it]}. Multi-write names are legal
    in this IR (optimizer ops write params in place via ``ParamOut``),
    so a rewrite that moves a READ of ``name`` across one of these
    indices — or points a reader past one at ``name`` directly — would
    observe the re-written value instead of the snapshot the
    eliminated op held. Every rewire/move guards with
    ``_written_between``."""
    w = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            w.setdefault(n, []).append(i)
    return w


def _written_between(widx, name, lo, hi):
    """True when ``name`` is written by an op with index in (lo, hi] —
    the unsafe interval for moving a read of ``name`` from ``lo`` to
    ``hi`` (or for redirecting a reader at ``hi`` to ``name`` as of
    ``lo``)."""
    return any(lo < k <= hi for k in widx.get(name, ()))


def _regions(ops):
    """Region id per op index: host ops and the autodiff marker are
    barriers (fusing across one would move computation between device
    segments or in/out of the differentiated prefix)."""
    rid, out = 0, []
    for op in ops:
        barrier = op.type == "autodiff" or bool(op.attrs.get("_host"))
        if barrier:
            rid += 1
        out.append(rid)
        if barrier:
            rid += 1
    return out


def _has_program_attr(op):
    """Control-flow ops carry sub-Programs in attrs (static/nested.py);
    their captures ride the input list, so reachability is sound, but
    value-rewrites must treat them as opaque."""
    from paddle_tpu.static.program import Program
    return any(isinstance(v, Program) for v in op.attrs.values())


def _protected_names(block, targets):
    """Vars no rewrite may erase or retype: fetch targets, persistable
    state, feed (is_data) vars."""
    prot = set(targets)
    for n, v in block.vars.items():
        if getattr(v, "persistable", False) or getattr(v, "is_data",
                                                       False):
            prot.add(n)
    return prot


def _rewire(block, old, new, skip_ops=()):
    """Point every reader of var ``old`` at ``new``."""
    for op in block.ops:
        if op in skip_ops:
            continue
        for slot, names in op.inputs.items():
            if old in names:
                op.inputs[slot] = [new if n == old else n for n in names]


def _single_consumer(cons_map, name, wcounts):
    """The one (index, op) consuming ``name``, or None if the var is
    multi-consumer, multi-writer, or unconsumed."""
    if wcounts.get(name, 0) != 1:
        return None
    cs = cons_map.get(name, [])
    if len(cs) != 1:
        return None
    i, op = cs[0]
    # an op reading the var in two slots counts once in the map; check
    # it reads it exactly once overall so rewires stay unambiguous
    if op.input_names().count(name) != 1:
        return None
    return cs[0]


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------
class ConstantFoldingPass(ProgramPass):
    """Evaluate ops whose inputs are all compile-time constants
    (``program._constants`` literals, or earlier folds) and record the
    result as a new constant (constant_folding_pass parity). Skips rng/
    host/side-effect/control-flow ops, persistable outputs, and results
    over ``max_elements`` (folding a giant fill into a materialized
    host array would trade compile-time work for trace memory)."""

    name = "constant_fold"

    def __init__(self, targets=(), max_elements=1 << 22):
        # targets accepted for pipeline-constructor uniformity but not
        # consulted: folding a FETCHED op is safe because fetch reads
        # the execution env, which is seeded from program._constants
        # (executor._compile / inference._build_pure_fn; pinned by
        # test_fetched_constant_output_still_fetchable)
        self.targets = set(targets)
        self.max_elements = int(max_elements)

    def apply(self, program):
        import jax.numpy as jnp

        from paddle_tpu.static.executor import exec_op

        blk = _block(program)
        consts = dict(getattr(program, "_constants", {}))
        wcounts = _write_counts(blk)
        kept = []
        for op in blk.ops:
            if (op.type == "autodiff" or op.attrs.get("_host")
                    or op.attrs.get("_needs_rng")
                    or op.type in _SIDE_EFFECT_TYPES
                    or _has_program_attr(op)):
                kept.append(op)
                continue
            ins = op.input_names()
            outs = op.output_names()
            if not outs or not all(n in consts for n in ins):
                kept.append(op)
                continue
            if any(wcounts.get(n, 0) != 1 for n in outs):
                kept.append(op)       # re-written name: order matters
                continue
            if any(blk.has_var(n)
                   and getattr(blk.vars[n], "persistable", False)
                   for n in outs):
                kept.append(op)       # state writes are never folded
                continue
            try:
                bound = exec_op(op, consts, None)
            except Exception:
                kept.append(op)       # not evaluable eagerly: leave it
                continue
            if sum(int(np.size(v)) for v in bound.values()) \
                    > self.max_elements:
                kept.append(op)
                continue
            for n, v in bound.items():
                consts[n] = jnp.asarray(v)
        if len(kept) != len(blk.ops):
            blk.ops = kept
            program._constants = consts
            program._bump()
        return program


class FoldScaleCastChainPass(ProgramPass):
    """scale→scale chains compose into one scale op; identity scales
    (x*1+0) and identity casts (target dtype == input var dtype) are
    dropped with their readers rewired."""

    name = "fold_scale_cast"

    def __init__(self, targets=()):
        self.targets = set(targets)

    @staticmethod
    def _affine(attrs):
        """(a, c) with y = a*x + c for one scale op."""
        s = float(attrs.get("scale", 1.0))
        b = float(attrs.get("bias", 0.0))
        if attrs.get("bias_after_scale", True):
            return s, b
        return s, b * s

    def apply(self, program):
        blk = _block(program)
        prot = _protected_names(blk, self.targets)
        changed = True
        while changed:
            changed = False
            wcounts = _write_counts(blk)
            cons = _consumer_map(blk)
            widx = _write_indices(blk)
            drop = set()

            def last_read(name, at):
                return max((k for k, _ in cons.get(name, ())),
                           default=at)

            for i, op in enumerate(blk.ops):
                if id(op) in drop:
                    continue
                if op.type == "scale":
                    src = op.inputs["X"][0]
                    out = op.outputs["Out"][0]
                    nxt = _single_consumer(cons, out, wcounts)
                    if (nxt is not None and nxt[1].type == "scale"
                            and out not in prot
                            and id(nxt[1]) not in drop
                            and not _written_between(widx, src, i,
                                                     nxt[0])):
                        a1, c1 = self._affine(op.attrs)
                        a2, c2 = self._affine(nxt[1].attrs)
                        nxt[1].inputs["X"] = list(op.inputs["X"])
                        nxt[1].attrs = {"scale": a1 * a2,
                                        "bias": c1 * a2 + c2,
                                        "bias_after_scale": True}
                        drop.add(id(op))
                        changed = True
                        continue
                    a, c = self._affine(op.attrs)
                    if a == 1.0 and c == 0.0 and out not in prot \
                            and wcounts.get(out, 0) == 1 \
                            and not _written_between(
                                widx, src, i, last_read(out, i)):
                        _rewire(blk, out, src, skip_ops=(op,))
                        drop.add(id(op))
                        changed = True
                elif op.type == "cast":
                    src = op.inputs["X"][0]
                    out = op.outputs["Out"][0]
                    v = blk.vars.get(src)
                    if v is None or v.dtype is None or out in prot \
                            or wcounts.get(out, 0) != 1 \
                            or _written_between(widx, src, i,
                                                last_read(out, i)):
                        continue
                    from paddle_tpu.core.dtypes import convert_dtype
                    try:
                        same = convert_dtype(
                            op.attrs.get("dtype")) == v.dtype
                    except Exception:
                        continue
                    if same:
                        _rewire(blk, out, src, skip_ops=(op,))
                        drop.add(id(op))
                        changed = True
            if drop:
                blk.ops = [o for o in blk.ops if id(o) not in drop]
                program._bump()
        return program


class CancelTransposeReshapePass(ProgramPass):
    """transpose∘transpose == identity and reshape∘reshape chains
    cancel/collapse; identity transposes (perm == iota) and identity
    reshapes (static target shape == static input shape) drop
    (transpose_flatten_concat_fuse_pass family, reduced to the
    provably-safe cases)."""

    name = "cancel_transpose_reshape"

    def __init__(self, targets=()):
        self.targets = set(targets)

    def apply(self, program):
        blk = _block(program)
        prot = _protected_names(blk, self.targets)
        changed = True
        while changed:
            changed = False
            wcounts = _write_counts(blk)
            cons = _consumer_map(blk)
            widx = _write_indices(blk)
            drop = set()

            def last_read(name, at):
                return max((k for k, _ in cons.get(name, ())),
                           default=at)

            for i, op in enumerate(blk.ops):
                if id(op) in drop:
                    continue
                if op.type == "transpose":
                    src = op.inputs["X"][0]
                    out = op.outputs["Out"][0]
                    perm = [int(p) for p in op.attrs.get("perm", [])]
                    if out in prot or wcounts.get(out, 0) != 1:
                        continue
                    if perm == list(range(len(perm))):
                        if _written_between(widx, src, i,
                                            last_read(out, i)):
                            continue
                        _rewire(blk, out, src, skip_ops=(op,))
                        drop.add(id(op))
                        changed = True
                        continue
                    nxt = _single_consumer(cons, out, wcounts)
                    if nxt is None or nxt[1].type != "transpose" \
                            or id(nxt[1]) in drop:
                        continue
                    perm2 = [int(p)
                             for p in nxt[1].attrs.get("perm", [])]
                    out2 = nxt[1].outputs["Out"][0]
                    if len(perm2) != len(perm) or out2 in prot \
                            or wcounts.get(out2, 0) != 1:
                        continue
                    composed = [perm[p] for p in perm2]
                    if composed == list(range(len(perm))):
                        # both cancel: readers of out2 read src
                        if _written_between(widx, src, i,
                                            last_read(out2, nxt[0])):
                            continue
                        _rewire(blk, out2, src, skip_ops=(op, nxt[1]))
                        drop.add(id(op))
                        drop.add(id(nxt[1]))
                    else:
                        # collapse into one transpose at the second
                        # op's position (which now reads src there)
                        if _written_between(widx, src, i, nxt[0]):
                            continue
                        nxt[1].inputs["X"] = [src]
                        nxt[1].attrs = dict(nxt[1].attrs)
                        nxt[1].attrs["perm"] = composed
                        drop.add(id(op))
                    changed = True
                elif op.type == "reshape":
                    src = op.inputs["X"][0]
                    out = op.outputs["Out"][0]
                    if out in prot or wcounts.get(out, 0) != 1:
                        continue
                    v_in = blk.vars.get(src)
                    shape = [int(s) for s in op.attrs.get("shape", [])]
                    if (v_in is not None and v_in.shape is not None
                            and all(d not in (-1, None)
                                    for d in v_in.shape)
                            and shape == [int(d) for d in v_in.shape]):
                        # identity reshape (fully static both sides)
                        if _written_between(widx, src, i,
                                            last_read(out, i)):
                            continue
                        _rewire(blk, out, src, skip_ops=(op,))
                        drop.add(id(op))
                        changed = True
                        continue
                    nxt = _single_consumer(cons, out, wcounts)
                    if nxt is None or nxt[1].type != "reshape" \
                            or id(nxt[1]) in drop \
                            or _written_between(widx, src, i, nxt[0]):
                        continue
                    shape2 = nxt[1].attrs.get("shape", [])
                    # a 0 entry copies the INPUT dim at that position
                    # — collapsing would re-anchor it on a different
                    # input, so only -1/positive target shapes collapse
                    if any(int(s) == 0 for s in shape2):
                        continue
                    nxt[1].inputs["X"] = [src]
                    drop.add(id(op))
                    changed = True
            if drop:
                blk.ops = [o for o in blk.ops if id(o) not in drop]
                program._bump()
        return program


class FuseMatmulBiasActPass(ProgramPass):
    """mul|matmul → elementwise_add(bias) → [relu|sigmoid|tanh|gelu]
    chains (the ``layers.fc`` emission, fc_fuse_pass parity) collapse
    into ONE ``fused_matmul`` op. Fires only when the intermediates
    are single-writer/single-consumer, unprotected, and the whole
    chain sits in one host/autodiff region."""

    name = "fuse_matmul_bias_act"

    def __init__(self, targets=()):
        self.targets = set(targets)

    def apply(self, program):
        blk = _block(program)
        prot = _protected_names(blk, self.targets)
        wcounts = _write_counts(blk)
        cons = _consumer_map(blk)
        widx = _write_indices(blk)
        regions = _regions(blk.ops)
        region_of = {id(op): regions[i] for i, op in enumerate(blk.ops)}
        index_of = {id(op): i for i, op in enumerate(blk.ops)}
        used = set()
        plans = []          # (member op ids, fused Operator, anchor id)
        for i, op in enumerate(blk.ops):
            if op.type not in _MATMUL_TYPES or id(op) in used:
                continue
            xs = op.inputs.get("X", [])
            if len(xs) != 2:
                continue
            mm_out = op.outputs["Out"][0]
            if mm_out in prot:
                continue
            nxt = _single_consumer(cons, mm_out, wcounts)
            if nxt is None or nxt[1].type != "elementwise_add" \
                    or id(nxt[1]) in used \
                    or region_of[id(nxt[1])] != regions[i]:
                continue
            j, add = nxt
            add_xs = add.inputs.get("X", [])
            # the matmul out must be the LEFT operand: axis-aligned
            # broadcast is defined on (big, small) operand order
            if len(add_xs) != 2 or add_xs[0] != mm_out \
                    or add_xs[1] == mm_out:
                continue
            add_out = add.outputs["Out"][0]
            members = [op, add]
            act = None
            anchor = add
            if add_out not in prot:
                nxt2 = _single_consumer(cons, add_out, wcounts)
                if nxt2 is not None and nxt2[1].type in _FUSABLE_ACTS \
                        and id(nxt2[1]) not in used \
                        and region_of[id(nxt2[1])] == regions[i] \
                        and not _attrs_nontrivial(nxt2[1]):
                    act = nxt2[1].type
                    anchor = nxt2[1]
                    members.append(nxt2[1])
            # the fused op reads the matmul operands and the bias at
            # the ANCHOR's (later) position — refuse if any is
            # re-written in the moved interval (in-place updates, e.g.
            # optimizer ParamOut, are legal in this IR; writes AFTER
            # the anchor are fine, the read still precedes them)
            anchor_idx = index_of[id(anchor)]
            if any(_written_between(widx, n, i, anchor_idx)
                   for n in xs) \
                    or _written_between(widx, add_xs[1], j,
                                        anchor_idx):
                continue
            from paddle_tpu.static.program import Operator
            mm_attrs = {k: v for k, v in op.attrs.items()
                        if k != "name" and v is not None}
            fused = Operator(
                blk, FUSED_MATMUL,
                inputs={"X": [xs[0], xs[1], add_xs[1]]},
                outputs={"Out": [anchor.outputs["Out"][0]]},
                attrs={"mm_type": op.type, "mm_attrs": mm_attrs,
                       "has_bias": True,
                       "bias_axis": add.attrs.get("axis", -1),
                       **({"act": act} if act else {})})
            used.update(id(m) for m in members)
            plans.append((set(id(m) for m in members), fused,
                          id(anchor)))
        if not plans:
            return program
        member_ids = set()
        fused_at = {}
        for ids, fused, anchor_id in plans:
            member_ids |= ids
            fused_at[anchor_id] = fused
        new_ops = []
        for op in blk.ops:
            if id(op) in fused_at:
                new_ops.append(fused_at[id(op)])
            elif id(op) not in member_ids:
                new_ops.append(op)
        blk.ops = new_ops
        program._bump()
        return program


def _attrs_nontrivial(op):
    """True when an activation op carries attrs beyond cosmetic
    defaults — such an op must not be absorbed into a fusion that
    replays it attr-free."""
    for k, v in op.attrs.items():
        if k in ("name",) or v is None:
            continue
        return True
    return False


class DeadOpEliminationPass(ProgramPass):
    """Drop ops whose outputs reach neither a fetch target, persistable
    state, a host/side-effect op, nor the autodiff marker — the
    backward_slice reachability core (prune.cc / dead-fetch
    elimination), applied at compile time against the step's actual
    fetch list."""

    name = "dead_op_elim"

    def __init__(self, targets=()):
        self.targets = set(targets)

    def apply(self, program):
        blk = _block(program)
        needed = set(self.targets)
        kept = []
        for op in reversed(blk.ops):
            keep = (bool(op.attrs.get("_host"))
                    or op.type == "autodiff"
                    or op.type in _SIDE_EFFECT_TYPES
                    or any(blk.has_var(n)
                           and getattr(blk.vars[n], "persistable",
                                       False)
                           for n in op.output_names())
                    or any(n in needed for n in op.output_names()))
            if keep:
                kept.append(op)
                needed.update(op.input_names())
        if len(kept) != len(blk.ops):
            kept.reverse()
            blk.ops = kept
            program._bump()
        return program


# ---------------------------------------------------------------------------
# pipeline drivers
# ---------------------------------------------------------------------------
class PipelineReport:
    """What one pipeline run did: per-pass op counts + total delta —
    the raw material of ``tools/dump_program.py --diff-passes`` and the
    ``bench.py passes`` evidence JSON."""

    def __init__(self):
        self.per_pass = []       # {"pass", "ops_before", "ops_after",
        #                           "ops_removed", "ms"}
        self.ops_before = 0
        self.ops_after = 0

    def ops_removed(self):
        return self.ops_before - self.ops_after

    def as_dict(self):
        return {"ops_before": self.ops_before,
                "ops_after": self.ops_after,
                "ops_removed": self.ops_removed(),
                "per_pass": [dict(p) for p in self.per_pass]}


def default_pipeline(targets=()):
    """The standard pass order. Folding runs first (it creates dead
    producers), shape/scale cleanups next (they expose adjacent
    chains), fusion after cleanups (so it sees the canonical chains),
    DCE last (it sweeps everything the others orphaned)."""
    return PassManager([
        ConstantFoldingPass(targets),
        FoldScaleCastChainPass(targets),
        CancelTransposeReshapePass(targets),
        FuseMatmulBiasActPass(targets),
        DeadOpEliminationPass(targets),
    ])


def _stamp_rng_indices(program):
    """Freeze each rng op's key-fold index BEFORE any op moves: the
    executor folds by ``_rng_idx`` when present, so optimization never
    shifts a dropout mask (optimized == legacy bit-for-bit)."""
    ops = program.global_block().ops
    h = 0
    for i, op in enumerate(ops):
        if op.attrs.get("_needs_rng") and "_rng_idx" not in op.attrs:
            op.attrs["_rng_idx"] = i - h
        if op.attrs.get("_host"):
            h += 1


def optimize_program(program, targets=(), pipeline=None, record=True,
                     cost_probe=None):
    """Clone ``program``, run the pass pipeline against ``targets``
    (the step's fetch names), publish per-pass evidence through
    ``monitor/cost.py``, and return ``(optimized_program, report)``.
    The input program is never mutated.

    ``cost_probe`` (optional, FLAGS_pass_cost_evidence): callable
    ``prog -> {"flops", "bytes"} | None`` probing XLA's analytical cost
    of the program as lowered. When given, it runs before the pipeline
    and after every pass; each pass's predicted delta (negative =
    cheaper) lands in its ``report.per_pass`` row and the
    ``program_pass_flops_delta`` / ``program_pass_bytes_delta``
    evidence gauges. Probe failures disable probing, never the
    pipeline."""
    from paddle_tpu.monitor import cost as _cost

    prog = program.clone()
    _stamp_rng_indices(prog)
    pm = pipeline or default_pipeline(targets)
    report = PipelineReport()
    report.ops_before = len(prog.global_block().ops)

    def _probe(p):
        nonlocal cost_probe
        if cost_probe is None:
            return None
        try:
            return cost_probe(p)
        except Exception:
            cost_probe = None
            return None

    cost0 = _probe(prog)
    for p in pm.passes:
        n0 = len(prog.global_block().ops)
        t0 = time.perf_counter()
        out = p.apply(prog)
        ms = (time.perf_counter() - t0) * 1e3
        prog = out if out is not None else prog
        n1 = len(prog.global_block().ops)
        pm.applied.append(p.name)
        row = {"pass": p.name, "ops_before": n0, "ops_after": n1,
               "ops_removed": n0 - n1, "ms": round(ms, 3)}
        flops_d = bytes_d = None
        if cost0 is not None:
            cost1 = _probe(prog)
            if cost1 is not None:
                flops_d = cost1["flops"] - cost0["flops"]
                bytes_d = cost1["bytes"] - cost0["bytes"]
                row["flops_delta"] = flops_d
                row["bytes_delta"] = bytes_d
                cost0 = cost1
        report.per_pass.append(row)
        if record:
            _cost.record_pass(p.name, ops_removed=n0 - n1, ms=ms,
                              flops_delta=flops_d, bytes_delta=bytes_d)
    # keep only constants a surviving op (or fetch target) still
    # reads: folding a const chain materializes every intermediate as
    # a device array, and the optimized clone lives in the executor's
    # compile cache — without this sweep each cached step would pin
    # the dead intermediates for the program's lifetime
    consts = getattr(prog, "_constants", None)
    if consts:
        live = set(targets)
        for op in prog.global_block().ops:
            live.update(op.input_names())
        prog._constants = {k: v for k, v in consts.items()
                           if k in live}
    report.ops_after = len(prog.global_block().ops)
    return prog, report


def optimize_for_execution(program, fetch_names, cost_probe=None):
    """The Executor's entry: optimize against the step's actual fetch
    list (persistable state writes are DCE roots by construction)."""
    prog, _ = optimize_program(program, targets=tuple(fetch_names),
                               cost_probe=cost_probe)
    return prog


def optimize_inference(program, fetch_names):
    """The export/serving entry — same pipeline; a separate name so the
    two call sites can diverge (e.g. inference-only layout passes)
    without touching the training path."""
    prog, _ = optimize_program(program, targets=tuple(fetch_names))
    return prog


# ---------------------------------------------------------------------------
# weight-only post-training quantization (export_aot cash-in)
# ---------------------------------------------------------------------------
def _mm_weight_slot(op):
    """The weight var name if ``op`` consumes its RHS in a
    quantization-compatible way ([in, out] layout, no transpose), else
    None."""
    xs = op.inputs.get("X", [])
    if op.type in _MATMUL_TYPES:
        if len(xs) != 2 or xs[0] == xs[1]:
            return None
        if op.type == "matmul" and op.attrs.get("transpose_y"):
            return None
        if op.type == "mul" and op.attrs.get("y_num_col_dims", 1) != 1:
            return None
        return xs[1]
    if op.type == FUSED_MATMUL:
        # xs[0] == xs[1] (self-product): only the RHS is dequantized,
        # so quantizing the shared operand would feed the LHS raw int8
        # — same guard as the raw-matmul branch above
        if len(xs) < 2 or xs[0] == xs[1] or op.attrs.get("quant"):
            return None
        mm_attrs = op.attrs.get("mm_attrs", {})
        if op.attrs.get("mm_type") == "matmul" \
                and mm_attrs.get("transpose_y"):
            return None
        if op.attrs.get("mm_type") == "mul" \
                and mm_attrs.get("y_num_col_dims", 1) != 1:
            return None
        return xs[1]
    return None


def plan_weight_quant(program, values, mode):
    """Names of weights eligible for weight-only PTQ: persistable 2-D
    float32 vars written by no op, consumed EXCLUSIVELY as the RHS of
    matmul/mul/fused_matmul ops in the standard [in, out] layout.
    ``values`` maps names to their trained arrays (shape/dtype
    evidence). Returns a sorted name list."""
    enforce(mode in ("int8", "bf16"),
            f"quantize mode must be 'int8' or 'bf16', got {mode!r}")
    blk = _block(program)
    written = {n for op in blk.ops for n in op.output_names()}
    cons = _consumer_map(blk)
    eligible = []
    for name, var in blk.vars.items():
        if not getattr(var, "persistable", False) or name in written:
            continue
        v = values.get(name)
        if v is None:
            continue
        v = np.asarray(v)
        if v.ndim != 2 or v.dtype != np.float32 or not v.size:
            continue
        readers = [op for _, op in cons.get(name, ())]
        if not readers:
            continue
        if all(_mm_weight_slot(op) == name for op in readers):
            eligible.append(name)
    return sorted(eligible)


def apply_weight_quant(program, weights, mode):
    """Clone ``program`` with each weight in ``weights`` retyped to its
    quantized storage dtype and every consuming matmul rewritten to a
    ``fused_matmul`` carrying the dequant (int8: + a per-channel
    ``<w>@quant_scale`` persistable input). Shared by ``export_aot``
    (which decides the list via ``plan_weight_quant``) and the serving
    warm boot (which applies the list the AOT manifest recorded) — the
    loader never re-derives eligibility, so a program/manifest mismatch
    fails loudly here instead of serving wrong bits."""
    enforce(mode in ("int8", "bf16"),
            f"quantize mode must be 'int8' or 'bf16', got {mode!r}")
    prog = program.clone()
    blk = _block(prog)
    wset = set(weights)
    missing = sorted(n for n in wset if n not in blk.vars)
    enforce(not missing,
            f"quantized weight(s) {missing[:3]} not in program — the "
            f"quant manifest does not match this model; re-export")
    for w in sorted(wset):
        var = blk.vars[w]
        enforce(var.shape is not None and len(var.shape) == 2,
                f"quantized weight {w!r} is not 2-D in this program")
        var.dtype = np.dtype("int8") if mode == "int8" \
            else _bf16_dtype()
        if mode == "int8":
            sv = blk.create_var(name=w + QUANT_SCALE_SUFFIX,
                                shape=[int(var.shape[1])],
                                dtype="float32")
            sv.persistable = True
    rewritten = 0
    for op in blk.ops:
        target = _mm_weight_slot(op)
        if target is None or target not in wset:
            # a non-matmul reader of a quantized weight means the plan
            # and this program disagree — loud, not wrong-math
            bad = sorted(set(op.input_names()) & wset)
            if bad:
                raise EnforceNotMet(
                    f"op {op.type!r} reads quantized weight "
                    f"{bad[0]!r} in a non-dequantizable position — "
                    f"the quant manifest does not match this model; "
                    f"re-export")
            continue
        xs = list(op.inputs["X"])
        new_xs, tail = xs[:2], xs[2:]
        if op.type in _MATMUL_TYPES:
            mm_attrs = {k: v for k, v in op.attrs.items()
                        if k != "name" and v is not None}
            op.attrs = {"mm_type": op.type, "mm_attrs": mm_attrs,
                        "has_bias": False, "quant": mode}
            op.type = FUSED_MATMUL
        else:                       # already fused_matmul
            op.attrs = dict(op.attrs)
            op.attrs["quant"] = mode
        if mode == "int8":
            new_xs.append(target + QUANT_SCALE_SUFFIX)
        new_xs.extend(tail)         # bias rides after the scale
        op.inputs["X"] = new_xs
        rewritten += 1
    enforce(rewritten > 0 or not wset,
            "quant rewrite matched no consuming matmul op")
    prog._bump()
    return prog


def _bf16_dtype():
    import jax.numpy as jnp
    return jnp.bfloat16


def quantize_weight_values(values, weights, mode):
    """{name: quantized array} (+ ``<name>@quant_scale`` float32 tables
    for int8) — per-output-channel abs-max over the [in, out] weight's
    columns, the ``fake_channel_wise_quantize_abs_max`` convention
    (ops/quantize.py) at quant_axis=1."""
    out = {}
    for w in weights:
        v = np.asarray(values[w], np.float32)
        if mode == "bf16":
            import jax.numpy as jnp
            out[w] = np.asarray(v, dtype=jnp.bfloat16)
            continue
        scale = np.max(np.abs(v), axis=0)        # [out] channels
        safe = np.maximum(scale, 1e-12)
        q = np.clip(np.round(v / safe[None, :] * QUANT_BINS),
                    -QUANT_BINS - 1, QUANT_BINS).astype(np.int8)
        out[w] = q
        out[w + QUANT_SCALE_SUFFIX] = scale.astype(np.float32)
    return out
