"""Structural (no-pickle) program serialization.

Parity: framework/framework.proto:43-188 — the reference serializes
ProgramDesc{BlockDesc{VarDesc,OpDesc}} as a protobuf, so loading a model
never executes code. The r2 build pickled the Program (arbitrary code
execution on load, VERDICT-r2 Weak #7); this module replaces that with a
schema'd JSON document:

- ops are (type, input slots, output slots, attrs),
- attrs are encoded structurally with tagged nodes for tuples, ndarrays,
  dtypes, nested Programs (control-flow sub-blocks — the analog of
  OpDesc BLOCK attrs, framework.proto:43), and registered framework
  objects (initializers, optimizers, clip/regularizer instances:
  {"__obj__": "paddle_tpu....Class", "state": {...}} rebuilt via
  __new__ + __dict__.update — never by calling into user code),
- decoding only instantiates classes inside the ``paddle_tpu.``
  namespace; anything else is a SerializationError, and Python callables
  (py_func host callbacks) are refused at save time with a clear error —
  the same programs the reference cannot deploy either.

Also provides ``program_fingerprint``: a canonical structural hash used
by the AOT index (inference.py) — stable across interpreter/numpy
versions, unlike hashing pickle bytes.
"""

import base64
import hashlib
import importlib
import json

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet

__all__ = [
    "SerializationError", "encode_value", "decode_value",
    "program_to_dict", "program_from_dict", "dumps_program",
    "loads_program", "program_fingerprint", "tree_manifest",
    "tree_from_manifest",
]

FORMAT_VERSION = 1
_TAGS = ("__tuple__", "__ndarray__", "__dtype__", "__obj__",
         "__program__", "__dict__", "__bytes__")


class SerializationError(EnforceNotMet):
    pass


def _is_program(v):
    from paddle_tpu.static.program import Program
    return isinstance(v, Program)


def encode_value(v, where=""):
    """Value -> JSON-able structure. ``where`` names the op/attr for
    error messages."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, bytes):
        return {"__bytes__": base64.b64encode(v).decode("ascii")}
    if isinstance(v, tuple):
        return {"__tuple__": [encode_value(x, where) for x in v]}
    if isinstance(v, list):
        return [encode_value(x, where) for x in v]
    if isinstance(v, np.dtype):
        return {"__dtype__": v.name}
    if isinstance(v, type) and issubclass(v, np.generic):
        return {"__dtype__": np.dtype(v).name}
    # jnp dtypes (e.g. jnp.float32 is a type handled above; dtype objs too)
    try:
        import jax.numpy as jnp
        if v is jnp.bfloat16 or getattr(v, "name", None) == "bfloat16":
            return {"__dtype__": "bfloat16"}
    except Exception:  # pragma: no cover
        pass
    if isinstance(v, np.ndarray) or type(v).__name__ == "ArrayImpl":
        arr = np.asarray(v)
        return {"__ndarray__": {
            "dtype": arr.dtype.name, "shape": list(arr.shape),
            "b64": base64.b64encode(
                np.ascontiguousarray(arr).tobytes()).decode("ascii")}}
    if _is_program(v):
        return {"__program__": program_to_dict(v)}
    if isinstance(v, dict):
        bad = [k for k in v if not isinstance(k, str)]
        if bad:
            raise SerializationError(
                f"{where}: dict attr has non-string keys {bad[:3]}")
        return {"__dict__": {k: encode_value(x, f"{where}.{k}")
                             for k, x in v.items()}}
    cls = type(v)
    mod = getattr(cls, "__module__", "")
    if mod.startswith("paddle_tpu.") or mod == "paddle_tpu":
        state = getattr(v, "__dict__", None)
        if state is None:
            raise SerializationError(
                f"{where}: {cls.__name__} has no __dict__ state")
        return {"__obj__": f"{mod}:{cls.__qualname__}",
                "state": {k: encode_value(x, f"{where}.{cls.__name__}.{k}")
                          for k, x in state.items()}}
    if callable(v):
        raise SerializationError(
            f"{where}: attr holds a Python callable "
            f"({getattr(v, '__name__', v)!r}) — py_func-style host "
            f"callbacks are not serializable (the reference cannot "
            f"deploy them either); express control flow through the "
            f"while_loop/static_rnn block builders, whose bodies are "
            f"sub-programs")
    raise SerializationError(
        f"{where}: cannot serialize attr of type {cls.__module__}."
        f"{cls.__qualname__}")


def _resolve_class(path):
    mod, _, qual = path.partition(":")
    if not (mod == "paddle_tpu" or mod.startswith("paddle_tpu.")):
        raise SerializationError(
            f"refusing to instantiate class outside paddle_tpu: {path}")
    obj = importlib.import_module(mod)
    for part in qual.split("."):
        obj = getattr(obj, part)
    if not isinstance(obj, type):
        raise SerializationError(f"{path} is not a class")
    return obj


def decode_value(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    if isinstance(v, dict):
        if "__tuple__" in v:
            return tuple(decode_value(x) for x in v["__tuple__"])
        if "__bytes__" in v:
            return base64.b64decode(v["__bytes__"])
        if "__dtype__" in v:
            name = v["__dtype__"]
            if name == "bfloat16":
                import jax.numpy as jnp
                return jnp.bfloat16
            return np.dtype(name)
        if "__ndarray__" in v:
            d = v["__ndarray__"]
            arr = np.frombuffer(
                base64.b64decode(d["b64"]),
                dtype=np.dtype(d["dtype"])).reshape(d["shape"])
            return arr.copy()   # writable, owned
        if "__program__" in v:
            return program_from_dict(v["__program__"])
        if "__dict__" in v:
            return {k: decode_value(x) for k, x in v["__dict__"].items()}
        if "__obj__" in v:
            cls = _resolve_class(v["__obj__"])
            obj = cls.__new__(cls)
            obj.__dict__.update(
                {k: decode_value(x) for k, x in v["state"].items()})
            return obj
    raise SerializationError(f"cannot decode node {v!r:.80}")


# ---------------------------------------------------------------------------
# Program <-> dict
# ---------------------------------------------------------------------------
def _var_to_dict(var):
    from paddle_tpu.static.program import Parameter
    try:
        dtype = np.dtype(var.dtype).name
    except TypeError:
        dtype = str(var.dtype)           # bfloat16 etc.
    d = {
        "name": var.name,
        "shape": None if var.shape is None else list(var.shape),
        "dtype": dtype,
        "persistable": bool(var.persistable),
        "stop_gradient": bool(var.stop_gradient),
        "is_data": bool(var.is_data),
        "lod_level": int(var.lod_level),
    }
    if isinstance(var, Parameter):
        d["is_parameter"] = True
        d["trainable"] = bool(var.trainable)
        d["optimize_attr"] = encode_value(var.optimize_attr,
                                          f"var {var.name}")
        d["regularizer"] = encode_value(var.regularizer, f"var {var.name}")
        d["do_model_average"] = bool(var.do_model_average)
        # initializer/gradient_clip are startup-time concerns; persisted
        # params carry values in the npz, but keep them for fidelity
        d["initializer"] = encode_value(var.initializer, f"var {var.name}")
        d["gradient_clip"] = encode_value(var.gradient_clip,
                                          f"var {var.name}")
    return d


def _var_from_dict(block, d):
    from paddle_tpu.static.program import Parameter, Variable
    if d.get("is_parameter"):
        v = Parameter(
            block, d["name"],
            tuple(d["shape"]) if d["shape"] is not None else None,
            d["dtype"], trainable=d.get("trainable", True),
            optimize_attr=decode_value(d.get("optimize_attr")),
            regularizer=decode_value(d.get("regularizer")),
            gradient_clip=decode_value(d.get("gradient_clip")),
            do_model_average=d.get("do_model_average", True),
            initializer=decode_value(d.get("initializer")))
    else:
        v = Variable(
            block, d["name"],
            tuple(d["shape"]) if d["shape"] is not None else None,
            d["dtype"], persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            is_data=d.get("is_data", False),
            lod_level=d.get("lod_level", 0))
    block.vars[d["name"]] = v
    return v


def program_to_dict(program):
    blk = program.global_block()
    ops = []
    for op in blk.ops:
        ops.append({
            "type": op.type,
            "inputs": {k: list(v) for k, v in op.inputs.items()},
            "outputs": {k: list(v) for k, v in op.outputs.items()},
            "attrs": {k: encode_value(v, f"op {op.type}, attr {k!r}")
                      for k, v in op.attrs.items()},
        })
    consts = {n: encode_value(np.asarray(c), f"constant {n}")
              for n, c in getattr(program, "_constants", {}).items()}
    return {
        "format_version": FORMAT_VERSION,
        "random_seed": int(getattr(program, "random_seed", 0)),
        "vars": [_var_to_dict(v) for v in blk.vars.values()],
        "ops": ops,
        "constants": consts,
    }


def program_from_dict(d):
    from paddle_tpu.static.program import Operator, Program
    ver = d.get("format_version")
    if ver != FORMAT_VERSION:
        raise SerializationError(
            f"unsupported program format version {ver!r}")
    program = Program()
    program.random_seed = d.get("random_seed", 0)
    blk = program.global_block()
    for vd in d["vars"]:
        _var_from_dict(blk, vd)
    for od in d["ops"]:
        op = Operator(blk, od["type"], None, None,
                      {k: decode_value(v)
                       for k, v in od.get("attrs", {}).items()})
        op.inputs = {k: list(v) for k, v in od.get("inputs", {}).items()}
        op.outputs = {k: list(v) for k, v in od.get("outputs", {}).items()}
        blk.ops.append(op)
    if d.get("constants"):
        import jax.numpy as jnp
        program._constants = {n: jnp.asarray(decode_value(c))
                              for n, c in d["constants"].items()}
    program._bump()
    return program


def dumps_program(program, extra=None):
    """Program (+ extra JSON-able metadata) -> JSON text."""
    doc = {"program": program_to_dict(program)}
    if extra:
        doc.update(extra)
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def loads_program(text):
    """JSON text -> (Program, full document dict)."""
    doc = json.loads(text)
    return program_from_dict(doc["program"]), doc


def program_fingerprint(program, feed_names=(), fetch_names=()):
    """Canonical structural hash of (program, feed, fetch) — the AOT
    index key. Stable across processes/numpy versions because it hashes
    the schema'd document, not pickle bytes."""
    doc = {"program": program_to_dict(program),
           "feeds": list(feed_names), "fetches": list(fetch_names)}
    blob = json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


# ---------------------------------------------------------------------------
# pytree manifests (checkpoints): npz + structural treedef, zero pickle
# ---------------------------------------------------------------------------
def tree_manifest(tree):
    """Pytree of arrays -> (manifest dict, {key: ndarray}). The manifest
    records the tree structure with array leaves replaced by npz keys;
    non-array leaves (ints, floats, strings) are stored inline."""
    arrays = {}
    counter = [0]

    def enc(x):
        if isinstance(x, (bool, int, float, str)) or x is None:
            return {"__leaf__": x}
        key = f"a{counter[0]}"
        counter[0] += 1
        arrays[key] = np.asarray(x)
        return {"__array__": key}

    def rec(node):
        if isinstance(node, dict):
            bad = [k for k in node if not isinstance(k, str)]
            if bad:
                raise SerializationError(
                    f"checkpoint tree has non-string dict keys "
                    f"{bad[:3]!r} — JSON manifests would silently "
                    f"stringify them; use string keys")
            return {"__d__": {k: rec(v) for k, v in node.items()}}
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            raise SerializationError(
                f"checkpoint tree contains a namedtuple "
                f"({type(node).__name__}) — it would restore as a plain "
                f"tuple; convert to a dict before saving")
        if isinstance(node, (list, tuple)):
            tag = "__l__" if isinstance(node, list) else "__t__"
            return {tag: [rec(v) for v in node]}
        return enc(node)

    return {"format_version": FORMAT_VERSION, "tree": rec(tree)}, arrays


def tree_from_manifest(manifest, arrays):
    """(manifest, npz mapping) -> pytree."""
    if manifest.get("format_version") != FORMAT_VERSION:
        raise SerializationError(
            f"unsupported manifest version "
            f"{manifest.get('format_version')!r}")

    def rec(node):
        if "__d__" in node:
            return {k: rec(v) for k, v in node["__d__"].items()}
        if "__l__" in node:
            return [rec(v) for v in node["__l__"]]
        if "__t__" in node:
            return tuple(rec(v) for v in node["__t__"])
        if "__leaf__" in node:
            return node["__leaf__"]
        if "__array__" in node:
            return arrays[node["__array__"]]
        raise SerializationError(f"bad manifest node {node!r:.60}")

    return rec(manifest["tree"])
