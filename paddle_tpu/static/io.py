"""Model save/load.

Parity: python/paddle/fluid/io.py (save_params:242, save_persistables:475,
load_params:527, load_persistables:714, save_inference_model:921,
load_inference_model:1109) and the save/load ops
(operators/save_op.cc, load_op.cc, save_combine_op.cc).

Format: params in a single .npz (the reference's save_combine "one file"
form); program IR as a schema'd JSON document (static/serialize.py —
the analog of the reference's ProgramDesc proto,
framework/framework.proto:184: loading a model never executes code;
pickle is banned from model artifacts, VERDICT-r2 Weak #7).
"""

import os

import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.static.executor import global_scope
from paddle_tpu.static.program import (
    OP_REGISTRY, Parameter, default_main_program,
)

PARAMS_FILE = "params.npz"
PROGRAM_FILE = "__model__"


def _collect(program, scope, predicate):
    out = {}
    for name, var in program.global_block().vars.items():
        if predicate(var):
            val = scope.find_var(name)
            if val is not None:
                out[name] = np.asarray(val)
    return out


def save_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    vals = _collect(main_program, global_scope(),
                    lambda v: isinstance(v, Parameter))
    np.savez(os.path.join(dirname, filename or PARAMS_FILE), **vals)


def save_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    vals = _collect(main_program, scope, lambda v: v.persistable)
    # optimizer state lives scope-side without block vars; include it
    for name in scope.names():
        if name not in vals and not name.startswith("@") \
                and scope.find_var(name) is not None \
                and not main_program.global_block().has_var(name):
            vals[name] = np.asarray(scope.find_var(name))
    np.savez(os.path.join(dirname, filename or PARAMS_FILE), **vals)


def _load_npz(path, scope):
    import jax.numpy as jnp
    with np.load(path, allow_pickle=False) as data:
        for name in data.files:
            scope.set_var(name, jnp.asarray(data[name]))


def load_params(executor, dirname, main_program=None, filename=None):
    _load_npz(os.path.join(dirname, filename or PARAMS_FILE),
              global_scope())


def load_persistables(executor, dirname, main_program=None, filename=None):
    _load_npz(os.path.join(dirname, filename or PARAMS_FILE),
              global_scope())


def _prune(program, feed_names, fetch_names):
    """Backward-reachability prune from fetches, stopping at feeds —
    io.py:921's prune+inference_optimize analog, expressed on the pass
    framework's slice+extract primitives (static/passes.py)."""
    from paddle_tpu.static.passes import backward_slice, extract_subprogram
    blk = program.global_block()
    kept, needed = backward_slice(blk, fetch_names,
                                  skip_types=("autodiff",))
    return extract_subprogram(program, kept, needed,
                              extra_vars=fetch_names)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, aot_shapes=None):
    """Freeze + prune + save. With ``aot_shapes`` (a list of
    {feed name: (shape, dtype)} buckets) the compiled executables are
    also serialized next to the model (paddle_tpu.inference.export_aot;
    ref capability: inference/io.cc serializes the optimized deployable
    model) so a Predictor loads without retracing or recompiling."""
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    fetch_names = [t if isinstance(t, str) else t.name for t in target_vars]
    inference_program = _prune(main_program.clone(for_test=True),
                               feeded_var_names, fetch_names)
    from paddle_tpu.static.serialize import dumps_program
    text = dumps_program(inference_program, extra={
        "feed_names": list(feeded_var_names),
        "fetch_names": fetch_names,
    })
    with open(os.path.join(dirname, model_filename or PROGRAM_FILE),
              "w") as f:
        f.write(text)
    vals = _collect(inference_program, global_scope(),
                    lambda v: v.persistable)
    np.savez(os.path.join(dirname, params_filename or PARAMS_FILE), **vals)
    if aot_shapes:
        from paddle_tpu import inference as _inf
        _inf.export_aot(dirname, inference_program,
                        list(feeded_var_names), fetch_names,
                        global_scope(), aot_shapes)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    from paddle_tpu.static.serialize import loads_program
    with open(os.path.join(dirname, model_filename or PROGRAM_FILE),
              "r") as f:
        program, doc = loads_program(f.read())
    _load_npz(os.path.join(dirname, params_filename or PARAMS_FILE),
              scope if scope is not None else global_scope())
    return program, doc["feed_names"], doc["fetch_names"]


# ---------------------------------------------------------------------------
# save/load as PROGRAM OPS (ref: operators/save_op.cc, load_op.cc,
# save_combine_op.cc, load_combine_op.cc — §5.4: "save/load are *ops*",
# so checkpointing can run inside any program). Host ops: the executor
# runs them eagerly between jitted device segments with real values.
# ---------------------------------------------------------------------------
def _save_op_compute(ins, attrs):
    path = attrs["file_path"]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path if path.endswith(".npz") else path + ".npz",
             **{n: np.asarray(v)
                for n, v in zip(attrs["var_names"], ins["X"])})
    return {}


def _load_op_compute(ins, attrs):
    path = attrs["file_path"]
    with np.load(path if path.endswith(".npz") else path + ".npz") as blob:
        return {"Out": [blob[n] for n in attrs["var_names"]]}


OP_REGISTRY["save_combine"] = _save_op_compute
OP_REGISTRY["load_combine"] = _load_op_compute


def append_save_op(program, vars_, file_path):
    """Append a save_combine op: every run of the program persists the
    named vars to ``file_path`` (the save_combine_op.cc single-file
    form). Must come after the vars' last write (e.g. after minimize)."""
    blk = program.global_block()
    names = [v if isinstance(v, str) else v.name for v in vars_]
    return blk.append_op("save_combine", inputs={"X": names}, outputs={},
                         attrs={"file_path": file_path,
                                "var_names": names, "_host": True})


def append_load_op(program, vars_, file_path):
    """Append a load_combine op writing the file's values into the named
    vars when the program runs (load_combine_op.cc)."""
    blk = program.global_block()
    names = [v if isinstance(v, str) else v.name for v in vars_]
    return blk.append_op("load_combine", inputs={},
                         outputs={"Out": names},
                         attrs={"file_path": file_path,
                                "var_names": names, "_host": True})


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """fluid.io.save_vars parity (io.py:108): save an explicit var list
    or every var matching ``predicate``."""
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    if vars is not None:
        names = [v if isinstance(v, str) else v.name for v in vars]
        vals = {}
        for n in names:
            val = scope.find_var(n)
            if val is None:
                raise EnforceNotMet(f"save_vars: var '{n}' not in scope")
            vals[n] = np.asarray(val)
    else:
        vals = _collect(main_program, scope,
                        predicate or (lambda v: v.persistable))
    np.savez(os.path.join(dirname, filename or PARAMS_FILE), **vals)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """fluid.io.load_vars parity (io.py:242): restore an explicit var
    list (or everything in the file when vars is None)."""
    import jax.numpy as jnp
    path = os.path.join(dirname, filename or PARAMS_FILE)
    scope = global_scope()
    want = None
    if vars is not None:
        want = {v if isinstance(v, str) else v.name for v in vars}
    elif predicate is not None:
        blk = (main_program or default_main_program()).global_block()
        want = {n for n, v in blk.vars.items() if predicate(v)}
    with np.load(path, allow_pickle=False) as data:
        missing = (want or set()) - set(data.files)
        if missing:
            raise EnforceNotMet(f"load_vars: not in file: {sorted(missing)}")
        for name in data.files:
            if want is None or name in want:
                scope.set_var(name, jnp.asarray(data[name]))
