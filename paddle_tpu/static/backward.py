"""Static autodiff.

Parity: python/paddle/fluid/backward.py append_backward:432 /
calc_gradient:695. The reference walks forward OpDescs in reverse emitting
grad ops from per-op GradOpDescMakers, de-duping with sum ops
(backward.py:135). The TPU-native design replaces the whole mechanism with
one `autodiff` pseudo-op marking "differentiate the block prefix w.r.t.
the trainable parameters": the Executor lowers it to
`jax.value_and_grad` over the traced prefix, so forward+backward compile
into one fused XLA computation and gradient de-dup/pruning fall out of
XLA's DCE instead of desc rewriting.

Gradient variables keep the reference's `<param>@GRAD` naming so
optimizer ops and user code match fluid.
"""

from paddle_tpu.static.program import Parameter, default_main_program

GRAD_SUFFIX = "@GRAD"


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append the autodiff marker and grad vars; returns
    [(param, grad_var)] like the reference."""
    program = loss.block.program
    blk = program.global_block()
    params = [p for p in blk.all_parameters() if p.trainable]
    if parameter_list:
        wanted = {p if isinstance(p, str) else p.name
                  for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    if no_grad_set:
        banned = {p if isinstance(p, str) else p.name for p in no_grad_set}
        params = [p for p in params if p.name not in banned]

    param_names = [p.name for p in params]
    grad_vars = []
    for p in params:
        g = blk.create_var(name=p.name + GRAD_SUFFIX, shape=p.shape,
                           dtype=p.dtype)
        grad_vars.append(g)
    blk.append_op(
        type="autodiff",
        inputs={"Loss": [loss.name]},
        outputs={"Grads": [g.name for g in grad_vars]},
        attrs={"loss": loss.name, "params": param_names,
               "checkpoint": bool(checkpoints)})
    program._loss_names.append(loss.name)
    return list(zip(params, grad_vars))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients parity (calc_gradient backward.py:695) — restricted
    form: targets is a single loss var, inputs are parameters/vars."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    pg = append_backward(t, parameter_list=[
        i if isinstance(i, str) else i.name
        for i in (inputs if isinstance(inputs, (list, tuple)) else [inputs])])
    return [g for _, g in pg]


# fluid name for the same entry point (backward.py:695)
calc_gradient = gradients
