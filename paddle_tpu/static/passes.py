"""Program-level pass framework: Pass / PassManager + pattern-match
and rewrite utilities.

Parity: the reference's IR pass infrastructure —
framework/ir/pass.h (Pass::Apply over a Graph), framework/ir/
graph_pattern_detector.h (PDPattern/PDNode subgraph matching), and
framework/ir/pass_builder.h (ordered pass pipelines). Here the Program
IS the IR (SURVEY §7: compile-level passes belong to XLA; program-level
rewrites operate on the op list), so a Pass transforms a Program and
the "pattern detector" matches over the op sequence with
producer/consumer indices instead of a graph object.

The rewrite utilities capture what every transpiler in this tree was
re-implementing by hand (walk ops, build a new list, insert/replace/
drop, rewire inputs): QuantizeTranspiler, QuantizationFreezePass and
the inference prune are expressed on these primitives (see
contrib/quant.py, static/io.py), and new rewrites (fusion experiments,
future freeze variants) compose the same way.
"""

import copy

from paddle_tpu.static.program import Operator, Program

__all__ = ["ProgramPass", "PassManager", "producers", "consumers",
           "match_ops", "match_chain", "backward_slice",
           "extract_subprogram", "BlockRewriter"]


class ProgramPass:
    """Base pass (framework/ir/pass.h Pass parity): ``apply`` takes a
    Program and returns it (rewritten in place or replaced)."""

    name = None

    def apply(self, program):
        raise NotImplementedError

    def __call__(self, program):
        return self.apply(program)


class PassManager:
    """Ordered pass pipeline (pass_builder.h parity). ``applied``
    records pass names for inspection/debugging."""

    def __init__(self, passes=()):
        self.passes = list(passes)
        self.applied = []

    def add(self, p):
        self.passes.append(p)
        return self

    def apply(self, program):
        for p in self.passes:
            out = p.apply(program) if hasattr(p, "apply") else p(program)
            program = out if out is not None else program
            self.applied.append(getattr(p, "name", None)
                                or getattr(p, "__name__", None)
                                or type(p).__name__)
        return program


# -- pattern matching ------------------------------------------------------

def producers(block):
    """{var name: (op index, op)} of the op that writes each var (last
    writer wins, matching execution order)."""
    out = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            out[n] = (i, op)
    return out


def consumers(block):
    """{var name: [(op index, op), ...]} of the ops reading each var."""
    out = {}
    for i, op in enumerate(block.ops):
        for n in op.input_names():
            out.setdefault(n, []).append((i, op))
    return out


def _matches(op, spec):
    """spec: an op type string, a tuple of types, or a predicate."""
    if callable(spec) and not isinstance(spec, str):
        return bool(spec(op))
    if isinstance(spec, (tuple, list, set, frozenset)):
        return op.type in spec
    return op.type == spec


def match_ops(program_or_block, spec):
    """[(index, op)] of ops matching ``spec`` in the global block (or
    the given block)."""
    blk = (program_or_block.global_block()
           if hasattr(program_or_block, "global_block")
           else program_or_block)
    return [(i, op) for i, op in enumerate(blk.ops)
            if _matches(op, spec)]


def match_chain(program_or_block, specs):
    """Producer->consumer chains (graph_pattern_detector's linked
    PDNodes): returns a list of op tuples (o1, ..., oN) where each
    o[k]'s output feeds o[k+1]'s input and o[k+1] matches specs[k+1].
    A var consumed by several matching ops yields one tuple each."""
    blk = (program_or_block.global_block()
           if hasattr(program_or_block, "global_block")
           else program_or_block)
    cons = consumers(blk)
    chains = [(op,) for _, op in match_ops(blk, specs[0])]
    for spec in specs[1:]:
        nxt = []
        for chain in chains:
            last = chain[-1]
            seen = set()
            for n in last.output_names():
                for _, op in cons.get(n, []):
                    if id(op) not in seen and _matches(op, spec):
                        seen.add(id(op))
                        nxt.append(chain + (op,))
        chains = nxt
    return chains


def backward_slice(block, target_names, stop_at=(), skip_types=()):
    """Ops needed (in order) to produce ``target_names``, walking
    backward from the targets and stopping at ``stop_at`` vars — the
    reachability core of prune/backward passes (ref: framework/
    prune.cc). Returns (kept ops list, needed var names set)."""
    needed = set(target_names)
    stop = set(stop_at)
    kept = []
    for op in reversed(block.ops):
        if op.type in skip_types:
            continue
        if any(n in needed for n in op.output_names()):
            kept.append(op)
            needed.update(n for n in op.input_names() if n not in stop)
    kept.reverse()
    return kept, needed


def extract_subprogram(program, kept_ops, needed_vars, extra_vars=()):
    """New Program holding copies of ``kept_ops`` and the var table
    entries they reference (the prune/clone tail every extraction pass
    repeats). Carries referenced program literals (_constants)."""
    blk = program.global_block()
    out = Program()
    ob = out.global_block()
    keep = set(needed_vars) | set(extra_vars)
    for name, var in blk.vars.items():
        if name in keep:
            nv = copy.copy(var)
            nv.block = ob
            ob.vars[name] = nv
    for op in kept_ops:
        new = Operator(ob, op.type, None, None, dict(op.attrs))
        new.inputs = {k: list(v) for k, v in op.inputs.items()}
        new.outputs = {k: list(v) for k, v in op.outputs.items()}
        ob.ops.append(new)
    consts = getattr(program, "_constants", None)
    if consts:
        out._constants = {n: v for n, v in consts.items()
                          if n in keep}
    out._bump()
    return out


# -- rewriting -------------------------------------------------------------

class BlockRewriter:
    """Queued rewrite over a block's op list, committed in one pass —
    the insert/replace/drop loop every transpiler hand-rolled.

    Usage::

        rw = BlockRewriter(program)
        for i, op in match_ops(program, "mul"):
            rw.insert_before(i, new_op)      # or replace(i, ...) etc.
        rw.commit()                          # rebuilds ops, bumps
    """

    def __init__(self, program):
        self.program = program
        self.block = program.global_block()
        self._before = {}      # index -> [ops]
        self._after = {}
        self._replace = {}     # index -> [ops] ([] means drop)

    def insert_before(self, index, *ops):
        self._before.setdefault(index, []).extend(ops)
        return self

    def insert_after(self, index, *ops):
        self._after.setdefault(index, []).extend(ops)
        return self

    def replace(self, index, *ops):
        self._replace[index] = list(ops)
        return self

    def remove(self, index):
        self._replace[index] = []
        return self

    def make_op(self, type, inputs=None, outputs=None, attrs=None):
        """Operator bound to this block WITHOUT appending (the raw
        Operator constructor's contract here)."""
        return Operator(self.block, type, inputs, outputs, attrs)

    def create_var(self, name, shape=None, dtype="float32", **kw):
        return self.block.create_var(name=name, shape=shape,
                                     dtype=dtype, **kw)

    def commit(self):
        n = len(self.block.ops)
        # insert_before(n) is the natural append form; anything beyond
        # (or any edit on a nonexistent index) is a pass bug that must
        # not vanish silently
        stray = {i for d in (self._before, self._after, self._replace)
                 for i in d if i > n or (i == n and d is not self._before)}
        if stray:
            raise IndexError(
                f"BlockRewriter: edits queued at out-of-range op "
                f"indices {sorted(stray)} (block has {n} ops)")
        new_ops = []
        for i, op in enumerate(self.block.ops):
            new_ops.extend(self._before.get(i, ()))
            new_ops.extend(self._replace.get(i, (op,)))
            new_ops.extend(self._after.get(i, ()))
        new_ops.extend(self._before.get(n, ()))
        self.block.ops = new_ops
        self._before, self._after, self._replace = {}, {}, {}
        self.program._bump()
        return self.program
