"""Program IR: Program / Block / Operator / Variable.

Parity: framework.proto ProgramDesc{BlockDesc{OpDesc,VarDesc}}
(ref: paddle/fluid/framework/framework.proto:43-188) and the python
builders (ref: python/paddle/fluid/framework.py Variable:376 Operator:985
Block:1436 Program:2775 Parameter:3589).

An Operator carries (type, input slots, output slots, attrs); semantics
come from OP_REGISTRY[type], a pure function over jax arrays — the
TPU-native replacement for the (place × dtype × layout) kernel registry
(ref: framework/op_registry.h, operator.cc:986 ChooseKernel). Because every
registered fn is traceable, a Block is a pure function of its inputs and
can be jitted whole.
"""

import contextlib
import copy
import threading

import numpy as np

from paddle_tpu.core.dtypes import convert_dtype, dtype_name
from paddle_tpu.core.enforce import EnforceNotMet, enforce

# ---------------------------------------------------------------------------
# op registry: type -> fn(inputs: dict[str, list], attrs: dict) -> dict
# ---------------------------------------------------------------------------
OP_REGISTRY = {}


def register_op(type_name, fn=None):
    """Register an op compute function. fn(ins, attrs) -> outs, where ins
    and outs are {slot: [array, ...]}."""
    def deco(f):
        OP_REGISTRY[type_name] = f
        return f
    if fn is not None:
        return deco(fn)
    return deco


def register_simple(type_name, fn, in_slots=("X",), out_slot="Out"):
    """Wrap a positional functional op: slots map to positional args,
    attrs to kwargs."""
    def compute(ins, attrs):
        args = []
        for s in in_slots:
            vals = ins.get(s, [])
            args.extend(vals)
        out = fn(*args, **attrs)
        return {out_slot: list(out) if isinstance(out, tuple) else [out]}
    OP_REGISTRY[type_name] = compute
    return compute


# ---------------------------------------------------------------------------
# IR node classes
# ---------------------------------------------------------------------------
class Variable:
    """Symbolic tensor in a Block (VarDesc parity)."""

    def __init__(self, block, name, shape=None, dtype="float32",
                 persistable=False, stop_gradient=False, is_data=False,
                 lod_level=0):
        self.block = block
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level

    @property
    def program(self):
        return self.block.program

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={dtype_name(self.dtype)})")

    # arithmetic sugar (framework.py monkey-patches these on Variable)
    def _binary(self, other, op_type):
        from paddle_tpu import layers
        return getattr(layers, op_type)(self, other)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")


class Parameter(Variable):
    """Parameter (framework.py:3589 parity): persistable + trainable with
    optimizer attributes."""

    def __init__(self, block, name, shape, dtype="float32", trainable=True,
                 optimize_attr=None, regularizer=None, gradient_clip=None,
                 do_model_average=True, initializer=None):
        super().__init__(block, name, shape, dtype, persistable=True)
        self.trainable = trainable
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        self.initializer = initializer


class Operator:
    """OpDesc parity: (type, inputs, outputs, attrs)."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: [v if isinstance(v, str) else v.name
                           for v in (vs if isinstance(vs, (list, tuple)) else [vs])]
                       for k, vs in (inputs or {}).items()}
        self.outputs = {k: [v if isinstance(v, str) else v.name
                            for v in (vs if isinstance(vs, (list, tuple)) else [vs])]
                        for k, vs in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"{{Op({self.type}): in={ins} out={outs}}}"


class Block:
    """BlockDesc parity: ordered ops + var table."""

    def __init__(self, program, idx=0, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    def create_var(self, name=None, shape=None, dtype="float32", **kw):
        from paddle_tpu.framework import unique_name
        name = name or unique_name.generate("tmp")
        v = Variable(self, name, shape, dtype, **kw)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype="float32", **kw):
        p = Parameter(self, name, shape, dtype, **kw)
        self.vars[name] = p
        return p

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise EnforceNotMet(f"Variable {name!r} not found in block "
                                f"{self.idx}")
        return v

    def has_var(self, name):
        return name in self.vars

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        enforce(type in OP_REGISTRY or type in ("autodiff",),
                f"op type {type!r} has no registered compute function")
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump()
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def __repr__(self):
        lines = [f"Block[{self.idx}] vars={len(self.vars)}"]
        lines += [f"  {op!r}" for op in self.ops]
        return "\n".join(lines)


class Program:
    """ProgramDesc parity. Single current block for now; sub-blocks are
    carried inside op attrs (structured control flow) rather than as flat
    block lists — lax.cond/scan hold their bodies the same way."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._seed_counter = 0
        # literal (non-Variable) operands captured at graph-build time,
        # name -> jnp array; Executor seeds the trace env with these
        self._constants = {}
        # bookkeeping used by append_backward / optimizers
        self._loss_names = []
        self._lr_schedulers = []
        # optional gradient clip installed by clip.set_gradient_clip
        self._grad_clip = None

    def _bump(self):
        self._version += 1

    @property
    def version(self):
        return self._version

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def global_block(self):
        return self.blocks[0]

    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        return list(self.global_block().vars.values())

    def clone(self, for_test=False):
        """Program.clone parity. for_test=True freezes dropout/batch_norm
        to inference behavior (the reference rewrites op attrs the same
        way, framework.py clone)."""
        p = Program()
        p.random_seed = self.random_seed
        p._constants = dict(self._constants)
        p._grad_clip = self._grad_clip
        blk = p.global_block()
        blk.vars = {n: copy.copy(v) for n, v in self.global_block().vars.items()}
        for v in blk.vars.values():
            v.block = blk
        for op in self.global_block().ops:
            attrs = dict(op.attrs)
            if for_test and "is_test" in _TEST_MODE_ATTRS.get(op.type, ()):
                attrs["is_test"] = True
            new = Operator(blk, op.type, None, None, attrs)
            new.inputs = {k: list(v) for k, v in op.inputs.items()}
            new.outputs = {k: list(v) for k, v in op.outputs.items()}
            blk.ops.append(new)
        p._bump()
        return p

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)


_TEST_MODE_ATTRS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
}


# ---------------------------------------------------------------------------
# default program machinery (framework.py default_main_program parity)
# ---------------------------------------------------------------------------
_tls = threading.local()


def _state():
    if not hasattr(_tls, "main"):
        _tls.main = Program()
        _tls.startup = Program()
        _tls.static_mode = False
    return _tls


def default_main_program():
    return _state().main


def default_startup_program():
    return _state().startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    st = _state()
    old = (st.main, st.startup, st.static_mode)
    st.main = main_program
    if startup_program is not None:
        st.startup = startup_program
    st.static_mode = True
    try:
        yield
    finally:
        st.main, st.startup, st.static_mode = old


def in_static_mode():
    return _state().static_mode


def enable_static():
    """Switch the ambient mode to static graph building (fluid's default
    posture): layer calls append ops to default_main_program(). Matches
    paddle.enable_static(); fluid-1.x-style scripts call this once at the
    top instead of wrapping everything in program_guard."""
    _state().static_mode = True


def disable_static():
    """Back to eager (dygraph) dispatch — the package default."""
    _state().static_mode = False


@contextlib.contextmanager
def static_mode_guard(on=True):
    st = _state()
    old = st.static_mode
    st.static_mode = on
    try:
        yield
    finally:
        st.static_mode = old


@contextlib.contextmanager
def name_scope(prefix):
    """fluid.name_scope parity (purely cosmetic here)."""
    yield


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True):
    """fluid.layers.data / fluid.data parity: declare a feed variable.

    append_batch_size=True prepends a batch dim (the fluid.layers.data
    convention where shape omits batch). ``None`` dims (the fluid.data /
    2.x spelling of "dynamic") normalize to -1."""
    shape = [-1 if s is None else int(s) for s in shape]
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    blk = default_main_program().global_block()
    v = blk.create_var(name=name, shape=shape, dtype=dtype, is_data=True,
                       lod_level=lod_level, stop_gradient=True)
    return v
