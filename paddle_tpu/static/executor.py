"""Executor + Scope.

Parity: python/paddle/fluid/executor.py (Executor:294, run:566, scope
machinery) and C++ framework/executor.cc.

TPU-native redesign: instead of the reference's per-op interpreter hot
loop (ref: executor.cc:417-421 `for op in ctx->ops_: op->Run`), `run()`
traces the whole block once through the functional op registry and caches
a `jax.jit`-compiled step
`(state, feeds, base_key, step_idx) -> (fetches, new_state)` — the
per-step rng key folds from (base_key, step_idx) INSIDE the compiled
program, so dispatch costs no eager device ops.
Persistable vars (parameters, optimizer moments, counters) are the carried
state pytree (donated, so updates are in-place in HBM). The autodiff
pseudo-op (see backward.py) is executed as `jax.value_and_grad` over the
prefix of the block — one fused XLA computation for
forward+backward+update, which is the entire point of the TPU design.
"""

import collections
import threading

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, enforce
from paddle_tpu.static.program import (
    OP_REGISTRY, Parameter, default_main_program, default_startup_program,
)


class Scope:
    """Name → value store (framework/scope.h parity, flattened: XLA owns
    device memory, so a scope is just the host-side name table)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)

    def set_var(self, name, value):
        self._vars[name] = value

    def drop_var(self, name):
        self._vars.pop(name, None)

    def names(self):
        return list(self._vars)


_global_scope = Scope()


class _ScopeStack(threading.local):
    """Per-thread scope stack rooted at the shared global scope.

    The stack must be thread-local: concurrent trainer threads (e.g. the
    in-process two-trainer PS tests, the reference's multi-threaded
    device workers) each `with scope_guard(their_scope)` — a shared
    stack would make one thread resolve global_scope() to another
    thread's scope mid-run (observed as "persistable vars not
    initialized" races). The root _global_scope itself stays shared, as
    in the reference (scope.h:45 global scope singleton)."""

    def __init__(self):
        self.stack = [_global_scope]


_scope_tls = _ScopeStack()


def global_scope():
    return _scope_tls.stack[-1]


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        _scope_tls.stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _scope_tls.stack.pop()


def _as_feed_array(v):
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        return jnp.asarray(v)
    return jnp.asarray(np.asarray(v))


def background_prefetch(producer, transform, depth=2):
    """Generic background-thread prefetch pipeline: a worker thread
    pulls items from ``producer`` (an iterable), applies ``transform``,
    and queues up to ``depth`` results ahead of the consumer
    (``depth <= 0`` = unbounded read-ahead). Producer exceptions
    re-raise in the consumer; early consumer exit drains the queue so
    the worker's blocked put can finish. Shared by device_prefetch and
    dataio's FileDataLoader."""
    import queue as _queue
    import threading

    q = _queue.Queue(maxsize=max(int(depth), 0))
    SENTINEL = object()
    stop = threading.Event()

    def worker():
        try:
            for b in producer:
                if stop.is_set():
                    return
                q.put(transform(b))
        except Exception as e:           # surface in consumer
            q.put(e)
            return
        q.put(SENTINEL)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except _queue.Empty:
            pass


def device_prefetch(batches, depth=2):
    """Double-buffered device staging (the role of the reference's
    operators/reader/buffered_reader.cc): a background thread transfers
    upcoming feed batches host->device ``depth`` steps ahead, so the
    H2D hop overlaps the current step's compute instead of serializing
    with it. ``batches`` yields feed dicts (or tuples/arrays); yields
    the same structure with device-resident arrays."""

    def stage(b):
        if isinstance(b, dict):
            return {k: _as_feed_array(v) for k, v in b.items()}
        if isinstance(b, (tuple, list)):
            return type(b)(_as_feed_array(v) for v in b)
        return _as_feed_array(b)

    return background_prefetch(batches, stage, depth)


def exec_op(op, env, key):
    """Run one program op through the functional registry: bind inputs
    from env, return {output name: value}. ``key`` is the op's rng key
    (None for ops without `_needs_rng`)."""
    fn = OP_REGISTRY[op.type]
    ins = {slot: [env[n] for n in names]
           for slot, names in op.inputs.items()}
    attrs = dict(op.attrs)
    if attrs.pop("_needs_rng", False):
        attrs["rng"] = key
    outs = fn(ins, attrs)
    bound = {}
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for n, v in zip(names, vals):
            bound[n] = v
    return bound


class Executor:
    """One compiled XLA computation per (program, feed-signature)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        self._keys = {}

    @staticmethod
    def _program_read_names(program):
        """Names of all vars the program's ops read, memoized on the
        program keyed by op count (the reader-protocol hot path calls
        run() in a tight loop and ops only ever get appended)."""
        ops = program.global_block().ops
        cached = getattr(program, "_read_names_cache", None)
        if cached is not None and cached[0] == len(ops):
            return cached[1]
        names = {n for op in ops for n in op.input_names()}
        program._read_names_cache = (len(ops), names)
        return names

    def _base_key(self, seed):
        k = self._keys.get(seed)
        if k is None:
            k = self._keys[seed] = jax.random.PRNGKey(seed)
        return k

    # -- public API --------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or default_main_program()
        # CompiledProgram.with_data_parallel: unwrap and remember the
        # data mesh; the same compiled step runs SPMD over it (GSPMD
        # partitions from the feed shardings — SURVEY §3.2's path with
        # the multi-device graph pass replaced by the partitioner)
        dp_mesh = None
        from paddle_tpu.compiler import CompiledProgram
        if isinstance(program, CompiledProgram):
            dp_mesh = program._mesh if program._dp else None
            program = program._program
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in fetch_list]
        if not feed:
            # non-iterable reader protocol (fluid.layers.py_reader
            # start()/reset()): pull the next batch from started readers
            # attached to this program; they raise EOFException when
            # exhausted (reader op EOF → core.EOFException parity).
            # Only readers whose vars the program actually reads are
            # pulled, and two started readers feeding the same var is an
            # error — a chained reader (open_files → batch) registers
            # both itself and its underlying py_reader, and silently
            # advancing both would skip data (ADVICE r3 #4).
            started = [r for r in getattr(program, "_py_readers", [])
                       if getattr(r, "_started", False)]
            read_names = (self._program_read_names(program)
                          | set(fetch_names) if started else set())
            # validate BEFORE pulling anything: raising mid-loop would
            # have already consumed a batch from an earlier reader
            pull, fed_by = [], {}
            for r in started:
                rnames = {v.name for v in r.vars}
                if read_names and not (rnames & read_names):
                    continue
                for n in rnames:
                    if n in fed_by:
                        raise EnforceNotMet(
                            f"two started readers would both feed var "
                            f"'{n}' — start only the outermost reader "
                            f"of a chain (e.g. the batch reader, not "
                            f"its underlying py_reader)")
                    fed_by[n] = r
                pull.append(r)
            for r in pull:
                feed.update(r._next_feed())
        scope = scope or global_scope()

        # startup-style programs (initializers only, no feeds) run eagerly
        if not feed and self._is_startup_like(program):
            self._run_eager(program, scope)
            return [] if not fetch_names else [
                self._fetch_value(scope, n, return_numpy) for n in fetch_names]

        feeds = {k: _as_feed_array(v) for k, v in feed.items()}
        state_names = self._state_names(program, scope)
        state = {n: scope.find_var(n) for n in state_names}
        # vars a host op (load_combine, ps_recv…) writes are initialized
        # BY the program — they may legitimately start uninitialized
        host_outs = {n for op in program.global_block().ops
                     if op.attrs.get("_host") for n in op.output_names()}
        missing = [n for n, v in state.items()
                   if v is None and n not in host_outs]
        if missing:
            raise EnforceNotMet(
                f"Persistable vars not initialized: {missing[:5]} — run the "
                f"startup program first (exe.run(startup_program))")
        state = {n: v for n, v in state.items() if v is not None}

        if dp_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from paddle_tpu.parallel.mesh import DATA_AXIS
            ndev = dp_mesh.size
            rep = NamedSharding(dp_mesh, PartitionSpec())

            def shard_leaf(v):
                if getattr(v, "ndim", 0) == 0:
                    return jax.device_put(v, rep)
                if v.shape[0] % ndev != 0:
                    raise EnforceNotMet(
                        f"data-parallel feed batch {v.shape[0]} is not "
                        f"divisible by the {ndev}-device data mesh")
                return jax.device_put(
                    v, NamedSharding(dp_mesh, PartitionSpec(DATA_AXIS)))
            feeds = {k: jax.tree.map(shard_leaf, v)
                     for k, v in feeds.items()}
            # persistable state rides replicated on the SAME mesh —
            # mixing single-device state with mesh-sharded feeds in one
            # jit is an error; re-put is a no-op once resident
            state = {k: jax.tree.map(lambda v: jax.device_put(v, rep), v)
                     for k, v in state.items()}

        sig = (id(program), program.version, id(dp_mesh),
               tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in feeds.items())),
               tuple(fetch_names), tuple(sorted(state_names)))
        step = self._cache.get(sig)
        if step is None:
            step = self._compile(program, sorted(state_names),
                                 sorted(feeds), fetch_names)
            self._cache[sig] = step

        # per-step rng: the base key is staged on device once per seed,
        # and the step fold happens INSIDE the jitted program (the old
        # eager PRNGKey+fold_in cost two device round-trips per step on
        # the remote-PJRT tunnel)
        base_key = self._base_key(program.random_seed)
        step_idx = np.uint32(scope.find_var("@step@") or 0)
        scope.set_var("@step@", (scope.find_var("@step@") or 0) + 1)
        fetches, new_state = step(state, feeds, base_key, step_idx)
        for n, v in new_state.items():
            scope.set_var(n, v)
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        return fetches

    # -- internals ---------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None,
                           fetch_list=None, fetch_info=None,
                           print_period=100, scope=None, debug=False):
        """Dataset-driven training loop (executor.py:927 parity, call
        stack SURVEY §3.4): iterate the dataset's batches, feed each into
        the compiled program, print fetches every ``print_period`` steps
        (the FetchConfig/LodTensorPrinter role). The reference's
        per-thread hogwild workers collapse into batched device steps."""
        enforce(dataset is not None, "dataset is required")
        fetch_list = fetch_list or []
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in fetch_list]
        enforce(fetch_info is None or len(fetch_info) == len(fetch_names),
                "fetch_info must match fetch_list in length")
        labels = fetch_info or fetch_names
        step = 0
        last = []
        # double-buffered device staging: H2D for batch n+1 overlaps
        # step n's compute (buffered_reader.cc role)
        for batch in device_prefetch(dataset):
            last = self.run(program, feed=batch, fetch_list=fetch_names,
                            scope=scope)
            step += 1
            if fetch_names and step % print_period == 0:
                msg = ", ".join(f"{l}={np.asarray(v).mean():.6f}"
                                for l, v in zip(labels, last))
                print(f"step {step}: {msg}")
        return last

    def infer_from_dataset(self, program=None, dataset=None,
                           fetch_list=None, fetch_info=None,
                           print_period=100, scope=None, debug=False):
        """executor.py infer_from_dataset parity — same loop; the caller
        passes an inference (for_test) program so no state is updated."""
        return self.train_from_dataset(program, dataset, fetch_list,
                                       fetch_info, print_period, scope,
                                       debug)

    def _is_startup_like(self, program):
        blk = program.global_block()
        return all(op.type != "autodiff" for op in blk.ops) and all(
            not (blk.has_var(n) and blk.var(n).is_data)
            for op in blk.ops for n in op.input_names())

    def _state_names(self, program, scope):
        blk = program.global_block()
        names = [n for n, v in blk.vars.items() if v.persistable]
        # include any extra persistables already living in the scope that
        # ops reference (optimizer state created lazily)
        for op in blk.ops:
            for n in op.input_names() + op.output_names():
                if scope.find_var(n) is not None and n not in names \
                        and not blk.has_var(n):
                    names.append(n)
        return names

    def _run_eager(self, program, scope):
        blk = program.global_block()
        key = self._base_key(program.random_seed)
        env = dict(getattr(program, "_constants", {}))
        env.update({n: scope.find_var(n) for n in scope.names()})
        for i, op in enumerate(blk.ops):
            op_key = (jax.random.fold_in(key, i)
                      if op.attrs.get("_needs_rng") else None)
            env.update(self._exec_op(op, env, op_key))
        for n, v in env.items():
            if v is not None:
                scope.set_var(n, v)

    def _exec_op(self, op, env, key):
        return exec_op(op, env, key)

    def _compile(self, program, state_names, feed_names, fetch_names):
        """Partition the block into maximal device runs, each jitted as
        ONE XLA computation (the whole block, in the common case), with
        host segments (attrs['_host']: RPC send/recv, py_func-style
        callbacks — ops the reference runs like any other in its per-op
        loop, executor.cc:417) executed eagerly between them. The
        PS-mode trainer program [ps_recv | fwd+bwd | ps_send] therefore
        still compiles its whole compute as a single fused program.

        Each op's rng key folds in its index *net of preceding host
        ops*, so a transpiler that brackets a program with host ops
        leaves the original ops' randomness (dropout masks…) unchanged
        — transpiled runs remain bit-comparable to local runs."""
        blk = program.global_block()
        ops = list(blk.ops)
        constants = dict(getattr(program, "_constants", {}))
        state_set = set(state_names)

        # a host op BEFORE the autodiff marker splits the differentiated
        # prefix across segments, so value_and_grad cannot see through it
        # and upstream params would silently train with zero grads. The
        # one legal shape is a host op whose outputs are exactly autodiff
        # roots (ps_recv delivering params): refuse everything else.
        ad_global = next((i for i, op in enumerate(ops)
                          if op.type == "autodiff"), None)
        if ad_global is not None:
            roots = set(ops[ad_global].attrs["params"])
            for i in range(ad_global):
                op = ops[i]
                outs = set(op.output_names())
                # a no-output host op (save_combine, barriers) still
                # splits the differentiated prefix — refuse it too
                if op.attrs.get("_host") and \
                        (not outs or not outs <= roots):
                    raise EnforceNotMet(
                        f"host op {op.type!r} at position {i} feeds the "
                        f"differentiated forward region — gradients cannot "
                        f"flow through a host boundary, so every parameter "
                        f"upstream of it would silently stop training. "
                        f"Move it after the loss/backward, or use a "
                        f"jax-traceable op instead")

        hosts_before = []              # rng index adjustment
        h = 0
        for op in ops:
            hosts_before.append(h)
            if op.attrs.get("_host"):
                h += 1

        segs = []                      # (is_host, start, end)
        i = 0
        while i < len(ops):
            j = i
            is_host = bool(ops[i].attrs.get("_host"))
            while j < len(ops) and bool(ops[j].attrs.get("_host")) == is_host:
                j += 1
            segs.append((is_host, i, j))
            i = j

        def interpret(env, lo, hi, base_key, step_idx):
            # lazy fold: host segments run eagerly, and most host ops
            # (RPC send/recv, save/load) take no rng — folding
            # unconditionally would cost device round-trips per host op.
            # Inside jitted segments the folds trace into the program.
            key = None
            for k in range(lo, hi):
                if ops[k].attrs.get("_needs_rng"):
                    if key is None:
                        key = jax.random.fold_in(base_key, step_idx)
                    op_key = jax.random.fold_in(key, k - hosts_before[k])
                else:
                    op_key = None
                env.update(self._exec_op(ops[k], env, op_key))
            return env

        def make_device_fn(lo, hi):
            ad = next((k for k in range(lo, hi)
                       if ops[k].type == "autodiff"), None)
            # only vars this segment WRITES may be donated: a donated
            # input that XLA merely forwards to an output (pass-through
            # state, e.g. a PS-mode trainer's orphaned optimizer step
            # counter) comes back as a deleted buffer and poisons the
            # scope for the next step
            writes = set()
            for k in range(lo, hi):
                writes.update(ops[k].output_names())

            def seg_fn(donated, rest, base_key, step_idx):
                # constants enter via closure -> XLA compile-time consts
                env = dict(constants)
                env.update(rest)
                env.update(donated)
                if ad is None:
                    env = interpret(env, lo, hi, base_key, step_idx)
                else:
                    adop = ops[ad]
                    loss_name = adop.attrs["loss"]
                    param_names = adop.attrs["params"]
                    base = {k: v for k, v in env.items()
                            if k not in param_names}

                    def fwd(params):
                        e = dict(base)
                        e.update(params)
                        e = interpret(e, lo, ad, base_key, step_idx)
                        return jnp.sum(e[loss_name]), e

                    params = {n: env[n] for n in param_names}
                    (_, env2), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params)
                    env = env2
                    for n in param_names:
                        env[n + "@GRAD"] = grads[n]
                    env = interpret(env, ad + 1, hi, base_key, step_idx)
                return {k: v for k, v in env.items() if k not in constants}

            return jax.jit(seg_fn, donate_argnums=(0,)), writes

        seg_fns = [None if is_host else make_device_fn(a, b)
                   for is_host, a, b in segs]

        def step(state, feeds, base_key, step_idx):
            env = dict(constants)
            env.update(state)
            env.update(feeds)
            for (is_host, a, b), fn_w in zip(segs, seg_fns):
                if is_host:
                    env = interpret(env, a, b, base_key, step_idx)
                else:
                    fn, writes = fn_w
                    # donate only state this segment overwrites (params,
                    # opt slots): feeds/constants may be reused by the
                    # caller, and donated pass-through state comes back
                    # as deleted buffers
                    donated = {k: env.pop(k) for k in list(env)
                               if k in state_set and k in writes}
                    rest = {k: v for k, v in env.items()
                            if k not in constants}
                    out = fn(donated, rest, base_key, step_idx)
                    env = dict(constants)
                    env.update(out)
            fetches = [env[n] for n in fetch_names]
            new_state = {n: env[n] for n in state_names}
            return fetches, new_state

        return step

    def _fetch_value(self, scope, name, return_numpy):
        v = scope.find_var(name)
        return np.asarray(v) if return_numpy and v is not None else v

    def close(self):
        self._cache.clear()


class AsyncExecutor:
    """async_executor.h:62 parity (the legacy pre-Trainer thread-pool
    trainer over DataFeed). On TPU the per-thread hogwild loops collapse
    into batched device steps, so this is a thin facade over
    Executor.train_from_dataset — kept because fluid user code
    instantiates fluid.AsyncExecutor(place) and calls run_from_files."""

    def __init__(self, place=None, run_mode=""):
        self._exe = Executor(place)

    def run(self, program, data_feed, filelist, thread_num, fetch,
            mode="", debug=False):
        data_feed.set_filelist(filelist)
        data_feed.set_thread(thread_num)
        return self._exe.train_from_dataset(
            program, data_feed,
            fetch_list=list(fetch) if fetch else None, debug=debug)

    run_from_files = run
