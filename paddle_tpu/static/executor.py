"""Executor + Scope.

Parity: python/paddle/fluid/executor.py (Executor:294, run:566, scope
machinery) and C++ framework/executor.cc.

TPU-native redesign: instead of the reference's per-op interpreter hot
loop (ref: executor.cc:417-421 `for op in ctx->ops_: op->Run`), `run()`
traces the whole block once through the functional op registry and caches
a `jax.jit`-compiled step
`(state, feeds, base_key, step_idx) -> (fetches, new_state)` — the
per-step rng key folds from (base_key, step_idx) INSIDE the compiled
program, so dispatch costs no eager device ops.
Persistable vars (parameters, optimizer moments, counters) are the carried
state pytree (donated, so updates are in-place in HBM). The autodiff
pseudo-op (see backward.py) is executed as `jax.value_and_grad` over the
prefix of the block — one fused XLA computation for
forward+backward+update, which is the entire point of the TPU design.

Dispatch hot path: the block compiles once, but the eager Python AROUND
the compiled step must not become the bottleneck either (ROADMAP: "as
fast as the hardware allows" — on a host-overhead-dominated model the
old per-step program rescans and DP re-`device_put`s WERE the step
time). `run()` therefore memoizes a prepared runner per
(program, feed-signature): state-name/host-out scans and signature
sorting happen once, DP-mode state stays resident on the mesh
(no re-put once placed), and `return_numpy=False` returns jax's async
device arrays so steps N+1.. dispatch while step N computes. The
prepared step also AOT warm-starts: `Executor.prepare()` lowers and
compiles eagerly, so with the persistent compilation cache
(core/compile_cache.py) a restarted worker replays the XLA compile from
disk. `FLAGS_executor_fast_path=0` restores the legacy per-step rescans
(the A/B lever bench_dispatch.py measures against).

Training-health hooks (docs/DEBUGGING.md): under `FLAGS_check_nan_inf`
each device segment also returns one fused isfinite-sentinel scalar,
verified before the step's new state reaches the scope — a trip runs
the eager bisecting localizer (monitor/numerics.py) and raises with
the first non-finite tensor/op named. Tensor-watch programs
(monitor/tensorwatch.py) get their `@watch@stats` vector auto-fetched
alongside the user's fetch list, and step wall time feeds the anomaly
detector (monitor/anomaly.py) when it is enabled.
"""

import itertools
import threading
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, enforce
from paddle_tpu.core.flags import define_flag, get_flag
from paddle_tpu.monitor import anomaly as _anomaly
from paddle_tpu.monitor import flight_recorder as _flight
from paddle_tpu.monitor import goodput as _goodput
from paddle_tpu.monitor import tensorwatch as _tensorwatch
from paddle_tpu.monitor import trace as _trace
from paddle_tpu.monitor.numerics import SENTINEL_KEY as _SENTINEL_KEY
from paddle_tpu.monitor.registry import counter as _counter
from paddle_tpu.monitor.registry import gauge as _gauge
from paddle_tpu.monitor.registry import histogram as _histogram
from paddle_tpu.profiler import RecordEvent
from paddle_tpu.static.program import (
    OP_REGISTRY, Parameter, default_main_program, default_startup_program,
)

define_flag("executor_fast_path", True,
            "Memoize a prepared runner per (program, feed-signature) so "
            "the steady-state step skips per-step state rescans and DP "
            "re-device_puts (0 = legacy per-step preparation)")
define_flag("monitor_cost", True,
            "Record per-compiled-segment FLOPs/bytes (XLA cost "
            "analysis) into the metrics registry on first execution "
            "(0 = skip the one-time extra lowering)")
define_flag("apply_ir_passes", True,
            "Run the program-level optimization pass pipeline "
            "(static/opt_passes.py: constant folding, matmul+bias+act "
            "fusion, transpose/reshape cancellation, dead-op "
            "elimination) before compiling each step; "
            "BuildStrategy.apply_ir_passes overrides per program "
            "(0 = bit-identical legacy lowering)")
define_flag("pass_cost_evidence", False,
            "Probe XLA's analytical FLOPs/bytes before the pass "
            "pipeline and after every pass, publishing per-pass "
            "predicted deltas (program_pass_flops_delta/_bytes_delta "
            "gauges + the pass_evidence table). One extra lowering per "
            "pass per compile signature — evidence tooling, off by "
            "default")

# unified telemetry (monitor/registry.py): the hot-loop counters every
# layer above reads — catalogued in docs/OBSERVABILITY.md
_m_steps = _counter("executor_steps_total",
                    "Executor.run calls that dispatched a step")
_m_step_ms = _histogram("executor_step_ms",
                        "Wall ms per Executor.run call (prepare + "
                        "dispatch + fetch)")
_m_fetch_ms = _histogram("executor_fetch_ms",
                         "Wall ms blocked materializing fetches "
                         "(host sync) per Executor.run call")
_m_retraces = _counter("executor_retraces_total",
                       "Device-segment traces performed (mirrors "
                       "Executor.trace_count across all executors)")
_m_q_depth = _gauge("prefetch_queue_depth",
                    "Items currently buffered in the background "
                    "prefetch queue")
_m_q_wait = _counter("prefetch_producer_wait_ms_total",
                     "Wall ms prefetch producers spent handing items "
                     "to the queue (blocked time on a full queue)")
_m_q_items = _counter("prefetch_items_total",
                      "Items produced by background prefetch pipelines")



class Scope:
    """Name → value store (framework/scope.h parity, flattened: XLA owns
    device memory, so a scope is just the host-side name table).

    ``version`` counts NAME-SET changes only (a var created or dropped),
    not value updates — the executor's prepared runners key on it to
    notice a scope gaining vars (lazily created optimizer state, host-op
    outputs) without rescanning the program every step."""

    def __init__(self):
        self._vars = {}
        self._version = 0

    @property
    def version(self):
        return self._version

    def var(self, name):
        if name not in self._vars:
            self._version += 1
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)

    def set_var(self, name, value):
        if name not in self._vars:
            self._version += 1
        self._vars[name] = value

    def drop_var(self, name):
        if name in self._vars:
            self._version += 1
        self._vars.pop(name, None)

    def names(self):
        return list(self._vars)


_global_scope = Scope()


class _ScopeStack(threading.local):
    """Per-thread scope stack rooted at the shared global scope.

    The stack must be thread-local: concurrent trainer threads (e.g. the
    in-process two-trainer PS tests, the reference's multi-threaded
    device workers) each `with scope_guard(their_scope)` — a shared
    stack would make one thread resolve global_scope() to another
    thread's scope mid-run (observed as "persistable vars not
    initialized" races). The root _global_scope itself stays shared, as
    in the reference (scope.h:45 global scope singleton)."""

    def __init__(self):
        self.stack = [_global_scope]


_scope_tls = _ScopeStack()


def global_scope():
    return _scope_tls.stack[-1]


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        _scope_tls.stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _scope_tls.stack.pop()


def _as_feed_array(v):
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        return jnp.asarray(v)
    return jnp.asarray(np.asarray(v))


class _PrefetchFailure:
    """Carrier for a producer-thread exception: the worker wraps instead
    of enqueueing the bare exception so (a) an Exception legitimately
    yielded as DATA is never mis-raised, and (b) the original traceback
    rides along explicitly and re-raises in the consumer with the
    producer frames intact. ``index`` is the ordinal of the item that
    failed (== items successfully produced before it), so a data-plane
    postmortem can name WHICH batch blew up, not just how."""

    __slots__ = ("exc", "index")

    def __init__(self, exc, index=None):
        self.exc = exc
        self.index = index


def background_prefetch(producer, transform, depth=2):
    """Generic background-thread prefetch pipeline: a worker thread
    pulls items from ``producer`` (an iterable), applies ``transform``,
    and queues up to ``depth`` results ahead of the consumer
    (``depth <= 0`` = unbounded read-ahead). Producer exceptions
    re-raise in the consumer with the producer's traceback; early
    consumer exit (break / .close()) stops and unblocks the worker —
    its puts time-slice against the stop flag, so it can never stay
    parked on a full queue after the consumer is gone. Shared by
    device_prefetch and dataio's FileDataLoader."""
    import queue as _queue
    import threading

    q = _queue.Queue(maxsize=max(int(depth), 0))
    SENTINEL = object()
    stop = threading.Event()

    def put(item, count=True):
        # never block forever: the consumer may have exited (its drain
        # can race with a worker still inside transform), so a plain
        # q.put could park this thread on a full queue for good
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
            except _queue.Full:
                continue
            if count:       # data items only, not sentinel/failure
                _m_q_items.inc()
                _m_q_wait.inc((time.perf_counter() - t0) * 1e3)
            _m_q_depth.set(q.qsize())
            return True
        return False

    # pipeline trace: the context is created on the CONSUMER thread
    # and the worker records its per-item spans against it — the
    # explicit cross-thread propagation monitor/trace.py is built on
    # (a postmortem/timeline then shows the producer's work under the
    # pipeline that owns it, not as orphan spans of an anonymous
    # thread)
    tctx = _trace.start_trace("prefetch/pipeline") \
        if _trace._enabled else None

    def worker():
        produced = 0
        try:
            for b in producer:
                if stop.is_set():
                    return
                if tctx is not None:
                    t0 = time.perf_counter()
                    item = transform(b)
                    _trace.record_span(tctx, "prefetch/item", t0,
                                       time.perf_counter(),
                                       attrs={"index": produced})
                else:
                    item = transform(b)
                if not put(item):
                    return
                produced += 1
        except BaseException as e:       # surface in consumer
            # `produced` == the failing item's ordinal: everything
            # before it was delivered downstream intact
            put(_PrefetchFailure(e, index=produced), count=False)
            return
        finally:
            # close the producer HERE, deterministically: a generator
            # holding file handles (dataio's record readers) would
            # otherwise keep them until GC when the consumer abandons
            # the pipeline early
            close = getattr(producer, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        put(SENTINEL, count=False)

    t = threading.Thread(target=worker, daemon=True,
                         name="pt-prefetch-worker")
    t.start()
    try:
        while True:
            if _goodput._armed:
                # consumer blocked on an empty queue = the input
                # pipeline couldn't keep up — the goodput ledger's
                # input_wait phase (docs/DEBUGGING.md "Where did my
                # wall-clock go?")
                _t_get = time.perf_counter()
                item = q.get()
                _goodput.attribute(time.perf_counter() - _t_get,
                                   phase="input_wait")
            else:
                item = q.get()
            _m_q_depth.set(q.qsize())
            if item is SENTINEL:
                break
            if isinstance(item, _PrefetchFailure):
                if _flight._enabled:
                    # the postmortem names the batch that failed, not
                    # just the exception: "batch 1337 of the stream"
                    # is what lets an operator replay/inspect the
                    # offending records
                    _flight.RECORDER.note(
                        "error", "prefetch.producer",
                        batch_index=item.index,
                        error=repr(item.exc))
                try:
                    item.exc.prefetch_batch_index = item.index
                except Exception:      # __slots__-restricted exception
                    pass
                raise item.exc.with_traceback(item.exc.__traceback__)
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except _queue.Empty:
            pass
        if tctx is not None:
            _trace.end_trace(tctx)


def device_prefetch(batches, depth=2, put=None):
    """Double-buffered device staging (the role of the reference's
    operators/reader/buffered_reader.cc): a background thread transfers
    upcoming feed batches host->device ``depth`` steps ahead, so the
    H2D hop overlaps the current step's compute instead of serializing
    with it. ``batches`` yields feed dicts (or tuples/arrays); yields
    the same structure with device-resident arrays. ``put`` overrides
    the per-batch placement — pass ``Executor.feed_stage(...)`` to
    stage batches directly onto the shardings the prepared runner
    consumes (DP/mesh feed placement) instead of the default device."""

    def stage(b):
        t0 = time.perf_counter()
        if put is not None:
            out = put(b)
        elif isinstance(b, dict):
            out = {k: _as_feed_array(v) for k, v in b.items()}
        elif isinstance(b, (tuple, list)):
            out = type(b)(_as_feed_array(v) for v in b)
        else:
            out = _as_feed_array(b)
        from paddle_tpu.dataio.dataloader import _m_h2d_ms
        _m_h2d_ms.inc((time.perf_counter() - t0) * 1e3)
        if put is None and _trace._enabled:
            # park the staging interval for the consuming step's trace
            # to adopt as its feed_stage phase (a feed_stage() put
            # notes for itself — see Executor.feed_stage); keyed by
            # the staged arrays' identity so only their consumer
            # adopts it
            _trace.stage_note("executor/feed_stage", t0,
                              time.perf_counter(),
                              key=_stage_key(out))
        return out

    return background_prefetch(batches, stage, depth)


def exec_op(op, env, key):
    """Run one program op through the functional registry: bind inputs
    from env, return {output name: value}. ``key`` is the op's rng key
    (None for ops without `_needs_rng`)."""
    fn = OP_REGISTRY[op.type]
    ins = {slot: [env[n] for n in names]
           for slot, names in op.inputs.items()}
    attrs = dict(op.attrs)
    # pass-pipeline bookkeeping (opt_passes._stamp_rng_indices), not a
    # compute kwarg — consumed by the caller's key derivation
    attrs.pop("_rng_idx", None)
    if attrs.pop("_needs_rng", False):
        attrs["rng"] = key
    outs = fn(ins, attrs)
    bound = {}
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for n, v in zip(names, vals):
            bound[n] = v
    return bound


def _stage_key(batch):
    """ids of the arrays a staged batch carries — the identity a
    stage note is matched to its consuming step by (trace.adopt_stage:
    an interleaved step that did NOT consume these arrays can never
    adopt their staging span)."""
    if isinstance(batch, dict):
        return [id(v) for v in batch.values()]
    if isinstance(batch, (tuple, list)):
        return [id(v) for v in batch]
    return [id(batch)]


_ABSENT = object()

#: PROCESS-GLOBAL per-run flow ids pairing each dispatch RecordEvent
#: with the fetch that materializes it (profiler.export_chrome_trace
#: draws the arrow by THIS id, not FIFO order — async steps emit
#: dispatches with no fetch, which made FIFO pairing hand a later
#: blocking step's fetch to the wrong dispatch). Global, not
#: per-Executor: all executors share one profiler ring, and
#: per-instance counters would collide ids across executors — the
#: same misattribution class the id pairing exists to kill.
_flow_ids = itertools.count(1)


def _spec_of(v):
    """jax.ShapeDtypeStruct for an array-like / (shape, dtype) pair /
    existing spec — the currency of AOT warm-start."""
    if isinstance(v, jax.ShapeDtypeStruct):
        return v
    if isinstance(v, tuple) and len(v) == 2 and not hasattr(v, "dtype"):
        return jax.ShapeDtypeStruct(tuple(v[0]), np.dtype(v[1]))
    return jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype
                                if not hasattr(v, "dtype") else v.dtype)


class _CompiledStep:
    """One compiled (program, signature) step: the block partitioned
    into host/device segments with each device segment jitted. Callable
    as (state, feeds, base_key, step_idx) -> (fetches, new_state)
    (plus the per-segment numerics sentinels when called with
    ``check=True`` — see monitor/numerics.py); also exposes the segment
    structure so `aot_compile` can lower+compile eagerly (warm-starting
    the persistent compilation cache) and the op list so the non-finite
    localizer can replay the step eagerly per-op."""

    __slots__ = ("segs", "seg_fns", "constants", "state_set",
                 "state_names", "fetch_names", "interpret", "ops",
                 "_donate_names", "donated_fetch_idx", "_cost_done",
                 "uid")

    #: process-unique compiled-step ids — the anomaly detector keys
    #: stall baselines on this, and a recycled id() of a GC'd step
    #: would hand a new program a dead program's baseline
    _uid_counter = itertools.count()

    def __init__(self, segs, seg_fns, constants, state_names,
                 fetch_names, interpret, ops):
        self._cost_done = False
        self.uid = next(_CompiledStep._uid_counter)
        self.segs = segs
        self.seg_fns = seg_fns
        self.constants = constants
        self.state_set = set(state_names)
        self.state_names = state_names
        self.fetch_names = fetch_names
        self.interpret = interpret
        self.ops = ops
        # per device segment: the state names it overwrites, frozen at
        # compile so the hot path does set-membership over a LIST of
        # candidates instead of scanning the whole env every step
        self._donate_names = [
            None if fn_w is None
            else [n for n in state_names if n in fn_w[2]]
            for fn_w in seg_fns]
        # fetches that alias DONATED state: the returned array is the
        # same buffer the next step donates, so an async caller
        # (return_numpy=False) must receive a copy or materialize-later
        # hits a deleted buffer
        donated = {n for d in self._donate_names if d for n in d}
        self.donated_fetch_idx = [i for i, n in enumerate(fetch_names)
                                  if n in donated]

    def _split(self, env, donate_names):
        # donate only state this segment overwrites (params, opt
        # slots): feeds/constants may be reused by the caller, and
        # donated pass-through state comes back as deleted buffers
        donated = {}
        for k in donate_names:
            v = env.pop(k, _ABSENT)
            if v is not _ABSENT:
                donated[k] = v
        if self.constants:
            rest = {k: v for k, v in env.items()
                    if k not in self.constants}
        else:
            rest = env
        return donated, rest

    def __call__(self, state, feeds, base_key, step_idx, check=False):
        """``check=True`` (FLAGS_check_nan_inf) runs the CHECKED jit
        variant of each device segment — same program plus one fused
        isfinite-reduction scalar — and donates nothing, so the
        pre-step state stays alive for the localizer's eager replay.
        Returns (fetches, new_state, sentinels) then; the plain
        2-tuple otherwise."""
        env = dict(self.constants) if self.constants else {}
        env.update(state)
        env.update(feeds)
        record_cost = not self._cost_done and \
            bool(get_flag("monitor_cost"))
        sentinels = []
        dev_i = 0
        for (is_host, a, b), fn_w, donate in zip(
                self.segs, self.seg_fns, self._donate_names):
            if is_host:
                env = self.interpret(env, a, b, base_key, step_idx)
            else:
                fn, checked_fn, _writes = fn_w
                use = checked_fn if check else fn
                donated, rest = self._split(env, () if check else donate)
                if record_cost:
                    # BEFORE executing: donation deletes these buffers
                    self._record_cost(dev_i, use, donated, rest,
                                      base_key, step_idx)
                out = use(donated, rest, base_key, step_idx)
                if check:
                    sentinels.append(out.pop(_SENTINEL_KEY))
                env = dict(self.constants) if self.constants else {}
                env.update(out)
                dev_i += 1
        if record_cost:
            # only latch when the probe actually ran: a step executed
            # under FLAGS_monitor_cost=0 can still record cost later
            # when the flag is flipped back on
            self._cost_done = True
        fetches = [env[n] for n in self.fetch_names]
        new_state = {n: env[n] for n in self.state_names}
        if check:
            return fetches, new_state, sentinels
        return fetches, new_state

    def _record_cost(self, dev_i, fn, donated, rest, base_key,
                     step_idx):
        """One-time per segment: read XLA's analytical FLOPs/bytes off
        ``fn.lower(...)`` and publish them as segment_flops/
        segment_bytes gauges — the raw material of the MFU estimate.
        The lowering shares jit's tracing cache, so it IS the first
        call's trace (trace_count moves exactly as without the probe)
        and the immediately following execution reuses it. Never
        fatal."""
        from paddle_tpu.monitor import cost as _cost
        try:
            lowered = fn.lower(donated, rest, base_key, step_idx)
        except Exception:
            return
        _cost.record_segment(id(self), dev_i,
                             _cost.analyze_lowered(lowered))

    def aot_compile(self, state, feeds, base_key, step_idx):
        """Eagerly .lower().compile() device segments with abstract
        inputs (``state``/``feeds`` values may be arrays, ShapeDtype-
        Structs, or (shape, dtype) pairs). With the persistent
        compilation cache enabled this writes the on-disk entries the
        first real step (and every restarted process) then compiles
        from. Host segments cannot run abstractly, so AOT stops at the
        first one; returns (compiled, total_device_segments)."""
        env = {k: _spec_of(v) for k, v in self.constants.items()}
        env.update({k: _spec_of(v) for k, v in state.items()})
        env.update({k: _spec_of(v) for k, v in feeds.items()})
        compiled = 0
        total = sum(1 for is_host, _, _ in self.segs if not is_host)
        record_cost = not self._cost_done and \
            bool(get_flag("monitor_cost"))
        for (is_host, a, b), fn_w, donate in zip(
                self.segs, self.seg_fns, self._donate_names):
            if is_host:
                break
            fn, _checked_fn, _writes = fn_w
            donated, rest = self._split(env, donate)
            lowered = fn.lower(donated, rest, base_key, step_idx)
            exe = lowered.compile()
            if record_cost:
                from paddle_tpu.monitor import cost as _cost
                _cost.record_segment(id(self), compiled,
                                     _cost.analyze_lowered(lowered))
                # collective bytes only exist POST-SPMD-partitioning,
                # i.e. in the compiled executable's optimized HLO —
                # AOT compile is the one place the executor holds it
                try:
                    txt = exe.as_text()
                except Exception:   # backend without HLO text
                    txt = None
                _cost.record_segment_comm(id(self), compiled,
                                          _cost.estimate_comm(txt))
                # memory analysis likewise lives on the COMPILED
                # executable (CompiledMemoryStats) — captured here so
                # the lazy first-call path never compiles twice just
                # to ask a footprint
                from paddle_tpu.monitor import memory as _memory
                _memory.record_segment_memory(
                    id(self), compiled, _memory.analyze_compiled(exe))
            out = jax.eval_shape(fn, donated, rest, base_key, step_idx)
            compiled += 1
            env = {k: _spec_of(v) for k, v in self.constants.items()}
            env.update(out)
        if record_cost and compiled == total:
            self._cost_done = True
        return compiled, total

    def lower_cost(self, state, feeds, base_key, step_idx):
        """Sum XLA's analytical FLOPs/bytes over the device segments by
        lowering them abstractly (no ``.compile()``, no metric
        recording) — the probe behind FLAGS_pass_cost_evidence. Host
        segments stop the walk like ``aot_compile``; returns
        ``{"flops", "bytes"}`` or None when nothing lowered."""
        env = {k: _spec_of(v) for k, v in self.constants.items()}
        env.update({k: _spec_of(v) for k, v in state.items()})
        env.update({k: _spec_of(v) for k, v in feeds.items()})
        from paddle_tpu.monitor import cost as _cost
        flops = bytes_ = 0.0
        lowered_any = False
        for (is_host, a, b), fn_w, donate in zip(
                self.segs, self.seg_fns, self._donate_names):
            if is_host:
                break
            fn, _checked_fn, _writes = fn_w
            donated, rest = self._split(env, donate)
            try:
                lowered = fn.lower(donated, rest, base_key, step_idx)
                est = _cost.analyze_lowered(lowered)
            except Exception:
                est = None
            if est:
                flops += float(est.get("flops") or 0.0)
                bytes_ += float(est.get("bytes") or 0.0)
                lowered_any = True
            out = jax.eval_shape(fn, donated, rest, base_key, step_idx)
            env = {k: _spec_of(v) for k, v in self.constants.items()}
            env.update(out)
        if not lowered_any:
            return None
        return {"flops": flops, "bytes": bytes_}


class _PreparedRunner:
    """Everything `Executor.run` needs per (program, feed-signature)
    that is invariant step to step — the product of the one-time scans
    the legacy path redid every call."""

    __slots__ = ("step", "state_names", "host_outs", "scope_ref",
                 "scope_version", "rep", "ok_shardings", "ndev",
                 "watch_idx", "spec", "targets")

    def __init__(self, step, state_names, host_outs, scope, rep, ndev,
                 watch_idx=None, spec=None, targets=None):
        self.step = step
        self.state_names = state_names
        self.host_outs = host_outs
        self.scope_ref = weakref.ref(scope)
        self.scope_version = scope.version
        self.watch_idx = watch_idx        # auto-appended @watch@stats
        self.rep = rep                    # replicated sharding (DP) or None
        self.spec = spec                  # ShardingSpec (mesh mode) or None
        # per-state-name target NamedSharding from the spec (replicated
        # for names the spec says nothing about) — the residency fast
        # path compares against THESE, so spec-sharded leaves pass
        # through without a per-step re-put just like replicated ones
        self.targets = targets
        # shardings proven equivalent to their name's target, memoized
        # BY IDENTITY with the object held alive: id alone could be
        # recycled by a new, non-equivalent sharding after GC
        self.ok_shardings = {}            # (name, id(s)) -> s
        self.ndev = ndev

    def fresh_for(self, scope):
        return (self.scope_ref() is scope
                and self.scope_version == scope.version)


class Executor:
    """One compiled XLA computation per (program, feed-signature)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}                  # full sig -> _CompiledStep
        self._runners = {}                # dispatch sig -> _PreparedRunner
        self._keys = {}
        self._trace_count = 0             # bumps per device-segment trace

    @property
    def trace_count(self):
        """Number of device-segment traces this executor performed —
        steady-state steps with an unchanged feed signature must not
        move it (the executor-caching tests pin exactly that)."""
        return self._trace_count

    @staticmethod
    def _program_read_names(program):
        """Names of all vars the program's ops read, memoized on the
        program keyed by op count (the reader-protocol hot path calls
        run() in a tight loop and ops only ever get appended)."""
        ops = program.global_block().ops
        cached = getattr(program, "_read_names_cache", None)
        if cached is not None and cached[0] == len(ops):
            return cached[1]
        names = {n for op in ops for n in op.input_names()}
        program._read_names_cache = (len(ops), names)
        return names

    def _base_key(self, seed):
        k = self._keys.get(seed)
        if k is None:
            k = self._keys[seed] = jax.random.PRNGKey(seed)
        return k

    @staticmethod
    def _passes_enabled(compiled):
        """Effective apply_ir_passes setting for one run: the wrapped
        program's ``BuildStrategy.apply_ir_passes`` when explicitly
        set, else ``FLAGS_apply_ir_passes`` (on by default). Off means
        the bit-identical legacy lowering — the A/B lever
        ``bench.py passes`` measures against."""
        on = bool(get_flag("apply_ir_passes"))
        if compiled is not None:
            bs = compiled.__dict__.get("_build_strategy")
            knob = getattr(bs, "apply_ir_passes", None) \
                if bs is not None else None
            if knob is not None:
                on = bool(knob)
        return on

    @staticmethod
    def _dispatch_sig(program, spec, feeds, fetch_names, scope,
                      apply_passes):
        """Prepared-runner cache key. The PROGRAM OBJECT itself (not
        id()) rides in the key: the dict entry then keeps it alive, so
        a dead program's id can never be recycled into a silent stale
        hit (dict hashing is identity-based for Program). The SPEC
        object (ShardingSpec of the mesh mode, or None) rides the same
        way — identity-hashed and kept alive by the entry. The scope is
        keyed by id() only — a recycled scope id is caught at use time
        by _PreparedRunner.fresh_for's weakref identity check, NOT by
        this key. ``apply_passes`` rides in the key so flipping the
        pass pipeline mid-process (the bench A/B) can never serve a
        step compiled under the other setting. feeds values may be
        arrays or ShapeDtypeStructs."""
        return (program, program.version, spec,
                tuple(sorted((k, tuple(v.shape), str(v.dtype))
                             for k, v in feeds.items())),
                tuple(fetch_names), id(scope), bool(apply_passes))

    def _store_runner(self, dsig, runner):
        # dead-scope eviction: a scope-per-request caller would
        # otherwise accumulate one unreachable runner per request; the
        # sweep is O(runners) and only runs when the table has grown
        if len(self._runners) > 32:
            self._runners = {k: r for k, r in self._runners.items()
                             if r.scope_ref() is not None}
        self._runners[dsig] = runner

    # -- public API --------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        """Run one step. ``return_numpy=False`` returns jax device
        arrays WITHOUT synchronizing — dispatch is async, so the caller
        can issue steps N+1..N+k while step N is still computing and
        only pay the sync when a value is materialized
        (``np.asarray``). ``return_numpy=True`` keeps the blocking
        fluid-parity contract."""
        program = program or default_main_program()
        # CompiledProgram.with_mesh_sharding / .with_data_parallel:
        # unwrap and remember the ShardingSpec; the same compiled step
        # runs SPMD over the spec's mesh (GSPMD partitions from the
        # spec-derived feed/state shardings plus the
        # with_sharding_constraint pins the compiled segments carry —
        # SURVEY §3.2's path with the multi-device graph pass replaced
        # by the partitioner)
        spec = None
        from paddle_tpu.compiler import CompiledProgram
        if isinstance(program, CompiledProgram):
            spec = program._spec
            apply_passes = self._passes_enabled(program)
            program = program._program
        else:
            apply_passes = self._passes_enabled(None)
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in fetch_list]
        if not feed:
            # non-iterable reader protocol (fluid.layers.py_reader
            # start()/reset()): pull the next batch from started readers
            # attached to this program; they raise EOFException when
            # exhausted (reader op EOF → core.EOFException parity).
            # Only readers whose vars the program actually reads are
            # pulled, and two started readers feeding the same var is an
            # error — a chained reader (open_files → batch) registers
            # both itself and its underlying py_reader, and silently
            # advancing both would skip data (ADVICE r3 #4).
            started = [r for r in getattr(program, "_py_readers", [])
                       if getattr(r, "_started", False)]
            read_names = (self._program_read_names(program)
                          | set(fetch_names) if started else set())
            # validate BEFORE pulling anything: raising mid-loop would
            # have already consumed a batch from an earlier reader
            pull, fed_by = [], {}
            for r in started:
                rnames = {v.name for v in r.vars}
                if read_names and not (rnames & read_names):
                    continue
                for n in rnames:
                    if n in fed_by:
                        raise EnforceNotMet(
                            f"two started readers would both feed var "
                            f"'{n}' — start only the outermost reader "
                            f"of a chain (e.g. the batch reader, not "
                            f"its underlying py_reader)")
                    fed_by[n] = r
                pull.append(r)
            for r in pull:
                feed.update(r._next_feed())
        scope = scope or global_scope()

        # startup-style programs (initializers only, no feeds) run eagerly
        if not feed and self._is_startup_like(program):
            self._run_eager(program, scope)
            return [] if not fetch_names else [
                self._fetch_value(scope, n, return_numpy) for n in fetch_names]

        t_run = time.perf_counter()
        if _goodput._armed:
            # goodput ledger boundary: the gap since the last run's
            # end (minus stalls the seams attributed) was device_idle
            _goodput.on_run_start(t_run)
        tc0 = self._trace_count
        # per-step trace (tail-sampled; monitor/trace.py): opened as
        # this thread's CURRENT trace so an anomaly/non-finite
        # postmortem fired mid-step embeds the phases recorded so far
        tctx = _trace.start_trace("executor/step", current=True) \
            if _trace._enabled else None
        if tctx is not None:
            # the root must start at t_run: the prepare child span is
            # stamped from t_run, and a child beginning before its own
            # root renders mis-nested in the merged timeline
            tctx.t0 = t_run
        try:
            with RecordEvent("executor.run/prepare"):
                feeds = {k: _as_feed_array(v) for k, v in feed.items()}
                dsig = self._dispatch_sig(program, spec, feeds,
                                          fetch_names, scope,
                                          apply_passes)
                fast = bool(get_flag("executor_fast_path"))
                runner = self._runners.get(dsig) if fast else None
                if runner is None or not runner.fresh_for(scope):
                    runner = self._prepare_runner(program, feeds, fetch_names,
                                                  scope, spec, apply_passes)
                    if fast:
                        self._store_runner(dsig, runner)
                state = self._gather_state(runner, scope)
                if state is None:             # scope changed under us
                    runner = self._prepare_runner(program, feeds, fetch_names,
                                                  scope, spec, apply_passes)
                    if fast:
                        self._store_runner(dsig, runner)
                    state = self._gather_state(runner, scope)

                if spec is not None:
                    feeds = spec.shard_feeds(feeds)
                    state = self._ensure_resident(state, runner, fast)
            t_prep = time.perf_counter()
            if tctx is not None:
                _trace.record_span(tctx, "executor/prepare", t_run,
                                   t_prep)
                # adopt the prefetch worker's staging interval for the
                # batch this step consumes: the span ran on the worker
                # thread (its tid says so) but belongs to THIS step's
                # tree. Matched BY ARRAY IDENTITY — only the note whose
                # staged arrays this step actually feeds is adopted, so an
                # interleaved manually-fed step (even one fed device_put
                # jax arrays) can neither steal a pipeline's note nor
                # shift later adoptions off by one.
                if feed:
                    _trace.adopt_stage(
                        tctx, match={id(v) for v in feed.values()})

            # per-step rng: the base key is staged on device once per seed,
            # and the step fold happens INSIDE the jitted program (the old
            # eager PRNGKey+fold_in cost two device round-trips per step on
            # the remote-PJRT tunnel)
            base_key = self._base_key(program.random_seed)
            step_idx = np.uint32(scope.find_var("@step@") or 0)
            scope.set_var("@step@", (scope.find_var("@step@") or 0) + 1)
            if tctx is not None:
                tctx.attrs["step"] = int(step_idx)
            check = bool(get_flag("check_nan_inf"))
            fid = next(_flow_ids)
            t_disp = time.perf_counter()
            with RecordEvent("executor.run/dispatch", args={"flow": fid}):
                try:
                    if check:
                        fetches, new_state, sentinels = runner.step(
                            state, feeds, base_key, step_idx, check=True)
                    else:
                        fetches, new_state = runner.step(
                            state, feeds, base_key, step_idx)
                except Exception as e:
                    from paddle_tpu.monitor import memory as _memory
                    if _memory.is_oom_error(e):
                        # typed OOM with attribution: ledger table, top
                        # live buffers, compile-time estimate vs limit,
                        # dumped via anomaly.trip("oom") (which embeds
                        # the in-flight trace). The BaseException
                        # handler below still ends the trace as error.
                        _memory.handle_oom(e, "executor.run/dispatch",
                                           step=int(step_idx))
                    raise
            t_disp_end = time.perf_counter()
            if tctx is not None:
                # recorded BEFORE the sentinel verification so a
                # non-finite trip's postmortem already names the dispatch
                # phase and its duration
                _trace.record_span(tctx, "executor/dispatch", t_disp,
                                   t_disp_end)
            if check:
                # the one deliberate host sync of the checked mode: a
                # scalar per segment, verified BEFORE the new state reaches
                # the scope so a trip leaves the pre-step params intact for
                # inspection. handle_trip localizes + raises.
                for seg_i, s in enumerate(sentinels):
                    if not bool(np.asarray(s)):
                        from paddle_tpu.monitor import numerics as _numerics
                        _numerics.handle_trip(runner.step, state, feeds,
                                              base_key, step_idx, seg_i)
            for n, v in new_state.items():
                scope.set_var(n, v)
            watch_v = None
            if runner.watch_idx is not None:
                # @watch@stats rides last in the fetch list (auto-appended
                # by _prepare_runner) — peel it off before the user sees
                # fetches; published after the step-time observation below
                watch_v = fetches.pop(runner.watch_idx)
            if return_numpy:
                with RecordEvent("executor.run/fetch", args={"flow": fid}):
                    t_fetch = time.perf_counter()
                    fetches = [np.asarray(f) for f in fetches]
                    _m_fetch_ms.observe(
                        (time.perf_counter() - t_fetch) * 1e3)
                if tctx is not None:
                    _trace.record_span(tctx, "executor/fetch", t_fetch,
                                       time.perf_counter())
            elif runner.step.donated_fetch_idx:
                # async contract: a fetched var that is also donated state
                # (e.g. fetch_list=[some_param]) would have its buffer
                # deleted by the NEXT step's donation before the caller
                # materializes it — hand back an (async) device copy
                for i in runner.step.donated_fetch_idx:
                    fetches[i] = jnp.array(fetches[i], copy=True)
            _m_steps.inc()
            step_ms = (time.perf_counter() - t_run) * 1e3
            _m_step_ms.observe(step_ms)
            if _goodput._armed:
                # close the ledger's in-run window: compile vs compute
                # (vs replay) split for this step
                _goodput.on_run_end(t_run, t_prep, t_disp, t_disp_end,
                                    self._trace_count > tc0)
            if watch_v is not None and _tensorwatch._enabled:
                _tensorwatch.on_step(watch_v, int(step_idx),
                                     sync=return_numpy)
            if _anomaly._enabled:
                # keyed by compiled-step identity: train and eval programs
                # through one executor get separate stall baselines
                _anomaly.DETECTOR.observe(step=int(step_idx),
                                          step_ms=step_ms,
                                          step_ms_key=runner.step.uid)
            if _flight._enabled:
                _flight.RECORDER.note("step", "executor.run",
                                      step=int(step_idx))
            if tctx is not None:
                # exemplar BEFORE the tail-sampling verdict (it force-
                # keeps the slowest step's tree), end AFTER the anomaly
                # feed above (a step_stall trip must still find this trace
                # in flight to embed it in its postmortem)
                _trace.record_exemplar("executor_step_ms", step_ms, tctx)
                _trace.end_trace(tctx)
            return fetches
        except BaseException:
            # a step that dies mid-flight (runner.step, a non-finite
            # sentinel trip, fetch) still ends its trace as an error:
            # errors are always kept by tail sampling, and leaving the
            # context in flight would pin _tls.current at a dead step
            # until the next run() on this thread. handle_trip /
            # anomaly postmortems embed the in-flight trace BEFORE
            # raising, so ending it here loses nothing.
            if tctx is not None:
                _trace.end_trace(tctx, error=True)
            raise

    def prepare(self, program=None, feed=None, fetch_list=None,
                scope=None):
        """AOT warm-start (jit .lower().compile() done eagerly): build
        the prepared runner for (program, feed-signature) and compile
        its device segments BEFORE the first step. ``feed`` maps names
        to sample arrays, (shape, dtype) pairs, or jax.ShapeDtypeStructs
        — only shapes/dtypes matter. Requires the startup program to
        have run (state shapes come from the scope).

        With the persistent compilation cache enabled
        (core/compile_cache.py, PADDLE_TPU_CACHE_DIR) the compiled
        executables land on disk, so the first real step — and every
        restarted worker process — replays the XLA compile as a disk
        read instead of recompiling. Returns True when every device
        segment was AOT-compiled (programs with host segments warm up
        to the first host boundary only)."""
        program = program or default_main_program()
        sspec = None
        from paddle_tpu.compiler import CompiledProgram
        if isinstance(program, CompiledProgram):
            sspec = program._spec
            apply_passes = self._passes_enabled(program)
            program = program._program
        else:
            apply_passes = self._passes_enabled(None)
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in fetch_list]
        scope = scope or global_scope()
        specs = {k: _spec_of(v if not isinstance(v, (list,))
                             else np.asarray(v))
                 for k, v in feed.items()}
        runner = self._prepare_runner(program, specs, fetch_names, scope,
                                      sspec, apply_passes)
        if bool(get_flag("executor_fast_path")):
            dsig = self._dispatch_sig(program, sspec, specs,
                                      fetch_names, scope, apply_passes)
            self._store_runner(dsig, runner)
        state = {}
        for n in runner.state_names:
            v = scope.find_var(n)
            if v is None:                 # host-written: materializes at
                continue                  # step time, can't be spec'd
            state[n] = v
        try:
            # ledger attribution of scope residency: optimizer slots
            # are named "<param>@<slot>" and internal optimizer state
            # leads with "@" — everything else is a persistable param
            from paddle_tpu.monitor import memory as _memory
            p_bytes = s_bytes = 0
            for n, v in state.items():
                nb = int(getattr(v, "nbytes", 0) or
                         np.asarray(v).nbytes)
                if "@" in n:
                    s_bytes += nb
                else:
                    p_bytes += nb
            _memory.ledger_set("train/params", p_bytes)
            if s_bytes:
                _memory.ledger_set("train/optimizer_slots", s_bytes)
        except Exception:
            pass
        if sspec is not None:
            # abstract inputs carry the SPEC-derived shardings, so the
            # AOT compile partitions exactly like the first real step
            state = {n: jax.ShapeDtypeStruct(
                        np.shape(v), v.dtype,
                        sharding=runner.targets[n])
                     for n, v in state.items()}
            specs = {
                k: jax.ShapeDtypeStruct(
                    s.shape, s.dtype,
                    sharding=sspec.feed_sharding(k, len(s.shape)))
                for k, s in specs.items()}
        base_key = self._base_key(program.random_seed)
        compiled, total = runner.step.aot_compile(
            state, specs, base_key, np.uint32(0))
        return compiled == total

    def feed_stage(self, program=None, feed_names=None):
        """Device-side double-buffer stage: returns ``put(batch)`` for
        a data loader's prefetch worker
        (``FileDataLoader(device_put=put)`` /
        ``device_prefetch(put=put)``) that places each feed batch on
        the EXACT sharding the prepared runner consumes — the
        spec-derived feed shardings for
        ``CompiledProgram.with_mesh_sharding`` / ``with_data_parallel``
        programs, the default device otherwise. The host->device hop
        for batch N+1 then runs in the worker thread while the
        compiled step for batch N computes, and ``run()`` passes the
        already-placed arrays through instead of re-putting them on
        its critical path (``dataio_h2d_overlap_ms`` counts the moved
        milliseconds). ``feed_names`` orders tuple/list batches (dict
        batches carry their own names; a bare-array batch needs
        exactly one name)."""
        program = program or default_main_program()
        spec = None
        from paddle_tpu.compiler import CompiledProgram
        if isinstance(program, CompiledProgram):
            spec = program._spec
        names = list(feed_names) if feed_names is not None else None

        def _staged(base_put):
            # tracing wrapper: the staging runs in a prefetch WORKER
            # thread, so the interval is parked as a stage note the
            # consuming step's trace adopts (monitor/trace.py) — one
            # `_enabled` check per batch when tracing is off
            def staged(batch):
                if not _trace._enabled:
                    return base_put(batch)
                t0 = time.perf_counter()
                out = base_put(batch)
                _trace.stage_note("executor/feed_stage", t0,
                                  time.perf_counter(),
                                  key=_stage_key(out))
                return out
            return staged

        if spec is None:
            return _staged(jax.device_put)

        def place(name, v):
            sh = spec.feed_sharding(name, np.ndim(v))
            s = getattr(v, "sharding", None)
            if s is not None:
                try:
                    if s == sh or s.is_equivalent_to(sh, np.ndim(v)):
                        return v
                except Exception:
                    pass
            return jax.device_put(v, sh)

        def put(batch):
            if isinstance(batch, dict):
                return {k: place(k, v) for k, v in batch.items()}
            if names is None:
                raise EnforceNotMet(
                    "feed_stage(feed_names=...) is required for "
                    "tuple/array batches — the spec's feed shardings "
                    "are name-keyed")
            if isinstance(batch, (tuple, list)):
                if len(batch) != len(names):
                    raise EnforceNotMet(
                        f"feed_stage got a {len(batch)}-field batch "
                        f"for feed_names={names}")
                return type(batch)(place(n, v)
                                   for n, v in zip(names, batch))
            if len(names) != 1:
                raise EnforceNotMet(
                    f"feed_stage got a single-array batch but "
                    f"{len(names)} feed_names — pass the one name "
                    f"this array feeds")
            return place(names[0], batch)

        return _staged(put)

    # -- internals ---------------------------------------------------------
    def _prepare_runner(self, program, feeds, fetch_names, scope, spec,
                        apply_passes=False):
        """The one-time (per feed-signature) preparation the legacy path
        performed every step: state-name/host-out scans, the
        initialization check, and the compiled-step lookup."""
        # pre-create the step counter: creating it AFTER this prepare
        # (on the first run) would bump scope.version and force one
        # spurious re-prepare — and drop the DP residency memo — at
        # step 2
        if scope.find_var("@step@") is None:
            scope.set_var("@step@", 0)
        # tensor-watch programs (minimize() under tensorwatch.enable())
        # carry an @watch@stats var: auto-fetch it so the stats ride the
        # step's existing materialization instead of a second dispatch
        watch_idx = None
        if program.global_block().has_var(_tensorwatch.STATS_VAR) \
                and _tensorwatch.STATS_VAR not in fetch_names:
            fetch_names = list(fetch_names) + [_tensorwatch.STATS_VAR]
            watch_idx = len(fetch_names) - 1
        state_names = self._state_names(program, scope)
        state = {n: scope.find_var(n) for n in state_names}
        # vars a host op (load_combine, ps_recv…) writes are initialized
        # BY the program — they may legitimately start uninitialized
        host_outs = {n for op in program.global_block().ops
                     if op.attrs.get("_host") for n in op.output_names()}
        missing = [n for n, v in state.items()
                   if v is None and n not in host_outs]
        if missing:
            raise EnforceNotMet(
                f"Persistable vars not initialized: {missing[:5]} — run the "
                f"startup program first (exe.run(startup_program))")
        rep = None
        ndev = 0
        targets = None
        if spec is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(spec.mesh, PartitionSpec())
            ndev = spec.mesh.size
            # per-name target shardings (replicated unless the spec
            # says otherwise), validated ONCE against the live state
            # shapes so a bad tiling fails here with the param named,
            # not deep inside the partitioner
            targets = spec.state_shardings(state_names)
            for n, v in state.items():
                if v is not None:
                    jax.tree.map(
                        lambda x, n=n: spec.validate_leaf(n, np.shape(x)),
                        v)
        # program OBJECT in the key (see _dispatch_sig): identity hash
        # plus a live reference — id() alone could be recycled by a new
        # program after GC and silently serve the stale compiled step.
        # The spec rides the same way (identity, kept alive).
        sig = (program, program.version, spec,
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feeds.items())),
               tuple(fetch_names), tuple(sorted(state_names)),
               bool(apply_passes))
        step = self._cache.get(sig)
        if step is None:
            cost_probe = None
            if apply_passes and bool(get_flag("pass_cost_evidence")):
                # FLAGS_pass_cost_evidence: lower each intermediate
                # program of the pass pipeline abstractly and hand XLA's
                # analytical FLOPs/bytes back to opt_passes, which
                # publishes the per-pass predicted delta
                # (program_pass_flops_delta / program_pass_bytes_delta
                # and the pass_evidence table). Needs the live shapes,
                # hence built here rather than in _compile.
                p_state = {n: v for n, v in state.items()
                           if v is not None}
                p_key = self._base_key(program.random_seed)

                def cost_probe(prog, _s=p_state, _f=dict(feeds),
                               _fn=tuple(fetch_names), _spec=spec,
                               _k=p_key):
                    probe_step = self._compile(
                        prog, sorted(_s), sorted(_f), list(_fn), _spec,
                        apply_passes=False)
                    return probe_step.lower_cost(_s, _f, _k,
                                                 np.uint32(0))
            step = self._compile(program, sorted(state_names),
                                 sorted(feeds), fetch_names, spec,
                                 apply_passes=apply_passes,
                                 cost_probe=cost_probe)
            self._cache[sig] = step
        return _PreparedRunner(step, state_names, host_outs, scope, rep,
                               ndev, watch_idx=watch_idx, spec=spec,
                               targets=targets)

    def _gather_state(self, runner, scope):
        """Pull the current state values for a prepared runner. Returns
        None when a state var has vanished from the scope (the caller
        re-prepares, which re-raises the proper diagnostic)."""
        state = {}
        host_outs = runner.host_outs
        for n in runner.state_names:
            v = scope.find_var(n)
            if v is None:
                if n not in host_outs:
                    return None
                continue
            state[n] = v
        return state

    def _ensure_resident(self, state, runner, fast):
        """Persistable state rides on the SAME mesh as the feeds, placed
        per the spec's per-name target sharding (replicated unless the
        spec shards it) — mixing single-device state with mesh-sharded
        feeds in one jit is an error. Fast path: once the step has run,
        its outputs already carry their target shardings (the compiled
        segments pin them with with_sharding_constraint), so re-putting
        every leaf every step (the legacy behavior, one eager dispatch
        per parameter per step) is pure overhead — a leaf whose
        sharding is provably equivalent to ITS name's target passes
        through untouched, spec-sharded leaves exactly like replicated
        ones, and the equivalence check memoizes on the (name, sharding
        object) pair (stable across steps: executables reuse their
        output shardings)."""
        rep = runner.rep
        targets = runner.targets
        ok = runner.ok_shardings
        out = {}
        for n, v in state.items():
            tgt = targets.get(n, rep) if targets is not None else rep

            def place_leaf(x, n=n, tgt=tgt):
                if fast:
                    s = getattr(x, "sharding", None)
                    if s is not None:
                        key = (n, id(s))
                        if ok.get(key) is s:
                            return x
                        try:
                            same = s == tgt or s.is_equivalent_to(
                                tgt, getattr(x, "ndim", 0))
                        except Exception:
                            same = False
                        if same:
                            ok[key] = s
                            return x
                return jax.device_put(x, tgt)

            out[n] = jax.tree.map(place_leaf, v)
        return out

    def train_from_dataset(self, program=None, dataset=None,
                           fetch_list=None, fetch_info=None,
                           print_period=100, scope=None, debug=False):
        """Dataset-driven training loop (executor.py:927 parity, call
        stack SURVEY §3.4): iterate the dataset's batches, feed each into
        the compiled program, print fetches every ``print_period`` steps
        (the FetchConfig/LodTensorPrinter role). The reference's
        per-thread hogwild workers collapse into batched device steps.

        Steps run with ``return_numpy=False`` and fetches only
        materialize (→ host sync) at ``print_period`` boundaries, so up
        to ``print_period`` steps stay in flight on the device queue
        while the host races ahead dispatching — pairing with
        ``device_prefetch``'s H2D double-buffering on the input side."""
        enforce(dataset is not None, "dataset is required")
        fetch_list = fetch_list or []
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in fetch_list]
        enforce(fetch_info is None or len(fetch_info) == len(fetch_names),
                "fetch_info must match fetch_list in length")
        labels = fetch_info or fetch_names
        step = 0
        last = []
        # double-buffered device staging: H2D for batch n+1 overlaps
        # step n's compute (buffered_reader.cc role)
        for batch in device_prefetch(dataset):
            last = self.run(program, feed=batch, fetch_list=fetch_names,
                            scope=scope, return_numpy=False)
            step += 1
            if fetch_names and step % print_period == 0:
                # the ONLY sync point in the steady loop
                last = [np.asarray(v) for v in last]
                msg = ", ".join(f"{l}={np.asarray(v).mean():.6f}"
                                for l, v in zip(labels, last))
                print(f"step {step}: {msg}")
        # materialize the tail so callers keep the numpy contract
        return [np.asarray(v) for v in last]

    def infer_from_dataset(self, program=None, dataset=None,
                           fetch_list=None, fetch_info=None,
                           print_period=100, scope=None, debug=False):
        """executor.py infer_from_dataset parity — same loop; the caller
        passes an inference (for_test) program so no state is updated."""
        return self.train_from_dataset(program, dataset, fetch_list,
                                       fetch_info, print_period, scope,
                                       debug)

    def _is_startup_like(self, program):
        blk = program.global_block()
        return all(op.type != "autodiff" for op in blk.ops) and all(
            not (blk.has_var(n) and blk.var(n).is_data)
            for op in blk.ops for n in op.input_names())

    def _state_names(self, program, scope):
        blk = program.global_block()
        names = [n for n, v in blk.vars.items() if v.persistable]
        # include any extra persistables already living in the scope that
        # ops reference (optimizer state created lazily)
        for op in blk.ops:
            for n in op.input_names() + op.output_names():
                if scope.find_var(n) is not None and n not in names \
                        and not blk.has_var(n):
                    names.append(n)
        return names

    def _run_eager(self, program, scope):
        blk = program.global_block()
        key = self._base_key(program.random_seed)
        env = dict(getattr(program, "_constants", {}))
        env.update({n: scope.find_var(n) for n in scope.names()})
        for i, op in enumerate(blk.ops):
            op_key = (jax.random.fold_in(key, i)
                      if op.attrs.get("_needs_rng") else None)
            env.update(self._exec_op(op, env, op_key))
        for n, v in env.items():
            if v is not None:
                scope.set_var(n, v)

    def _exec_op(self, op, env, key):
        return exec_op(op, env, key)

    def _compile(self, program, state_names, feed_names, fetch_names,
                 spec=None, apply_passes=False, cost_probe=None):
        """Partition the block into maximal device runs, each jitted as
        ONE XLA computation (the whole block, in the common case), with
        host segments (attrs['_host']: RPC send/recv, py_func-style
        callbacks — ops the reference runs like any other in its per-op
        loop, executor.cc:417) executed eagerly between them. The
        PS-mode trainer program [ps_recv | fwd+bwd | ps_send] therefore
        still compiles its whole compute as a single fused program.

        Each op's rng key folds in its index *net of preceding host
        ops*, so a transpiler that brackets a program with host ops
        leaves the original ops' randomness (dropout masks…) unchanged
        — transpiled runs remain bit-comparable to local runs."""
        if apply_passes:
            # program-level pass pipeline (static/opt_passes.py): runs
            # on a CLONE against this step's actual fetch list, so the
            # caller's program object — and the apply_ir_passes=False
            # legacy lowering — stay bit-identical. Per-pass evidence
            # lands in monitor/cost.py (program_pass_* metrics). Rng
            # ops carry _rng_idx stamps, so optimization never shifts
            # a dropout mask.
            from paddle_tpu.static import opt_passes as _opt
            program = _opt.optimize_for_execution(program, fetch_names,
                                                  cost_probe=cost_probe)
        blk = program.global_block()
        ops = list(blk.ops)
        constants = dict(getattr(program, "_constants", {}))
        state_set = set(state_names)

        # ShardingSpec lowering: names the spec annotates (params and
        # their @GRADs) are pinned with with_sharding_constraint inside
        # every jitted segment — the pjit path (parallel/_compat.py;
        # the jax pin has no shard_map), so GSPMD partitions the fused
        # step exactly per the program-level annotations instead of
        # guessing from inputs alone. Lookup is memoized per name;
        # names the spec says nothing about are left to the
        # partitioner (the pure-DP default spec pins nothing, keeping
        # that lowering bit-identical to the pre-spec executor).
        c_memo = {}

        def _target(n, state_default=False):
            """Constraint target for name ``n``: the spec's explicit
            entry (params and their @GRADs), or — with
            ``state_default`` — the replicated default for UNSPEC'D
            state names. Segment OUTPUTS pin every state name: left
            free, GSPMD may pick a sharded layout for an unannotated
            param (observed: P('model') chosen for a replicated-target
            leaf), which both breaks the "replicated unless spec'd"
            state contract and defeats the residency fast path into a
            re-put per leaf per step."""
            if spec is None:
                return None
            key = (n, state_default)
            t = c_memo.get(key, _ABSENT)
            if t is _ABSENT:
                t = spec.constraint_for(n)
                if t is None and state_default and n in state_set:
                    t = spec.param_sharding(n)
                c_memo[key] = t
            return t

        def _pin(env, state_default=False):
            if spec is None:
                return env
            from paddle_tpu.parallel._compat import sharding_constraint
            for n in list(env):
                t = _target(n, state_default)
                if t is not None:
                    env[n] = sharding_constraint(env[n], spec.mesh, t)
            return env

        # a host op BEFORE the autodiff marker splits the differentiated
        # prefix across segments, so value_and_grad cannot see through it
        # and upstream params would silently train with zero grads. The
        # one legal shape is a host op whose outputs are exactly autodiff
        # roots (ps_recv delivering params): refuse everything else.
        ad_global = next((i for i, op in enumerate(ops)
                          if op.type == "autodiff"), None)
        if ad_global is not None:
            roots = set(ops[ad_global].attrs["params"])
            for i in range(ad_global):
                op = ops[i]
                outs = set(op.output_names())
                # a no-output host op (save_combine, barriers) still
                # splits the differentiated prefix — refuse it too
                if op.attrs.get("_host") and \
                        (not outs or not outs <= roots):
                    raise EnforceNotMet(
                        f"host op {op.type!r} at position {i} feeds the "
                        f"differentiated forward region — gradients cannot "
                        f"flow through a host boundary, so every parameter "
                        f"upstream of it would silently stop training. "
                        f"Move it after the loss/backward, or use a "
                        f"jax-traceable op instead")

        hosts_before = []              # rng index adjustment
        h = 0
        for op in ops:
            hosts_before.append(h)
            if op.attrs.get("_host"):
                h += 1

        segs = []                      # (is_host, start, end)
        i = 0
        while i < len(ops):
            j = i
            is_host = bool(ops[i].attrs.get("_host"))
            while j < len(ops) and bool(ops[j].attrs.get("_host")) == is_host:
                j += 1
            segs.append((is_host, i, j))
            i = j

        def interpret(env, lo, hi, base_key, step_idx):
            # lazy fold: host segments run eagerly, and most host ops
            # (RPC send/recv, save/load) take no rng — folding
            # unconditionally would cost device round-trips per host op.
            # Inside jitted segments the folds trace into the program.
            key = None
            for k in range(lo, hi):
                if ops[k].attrs.get("_needs_rng"):
                    if key is None:
                        key = jax.random.fold_in(base_key, step_idx)
                    # _rng_idx (stamped by the pass pipeline before any
                    # op moved) pins the fold index an optimized op had
                    # in the ORIGINAL program — masks stay bit-identical
                    # to the unoptimized lowering
                    idx = ops[k].attrs.get("_rng_idx")
                    if idx is None:
                        idx = k - hosts_before[k]
                    op_key = jax.random.fold_in(key, idx)
                else:
                    op_key = None
                env.update(self._exec_op(ops[k], env, op_key))
            return env

        def make_device_fn(lo, hi):
            ad = next((k for k in range(lo, hi)
                       if ops[k].type == "autodiff"), None)
            # only vars this segment WRITES may be donated: a donated
            # input that XLA merely forwards to an output (pass-through
            # state, e.g. a PS-mode trainer's orphaned optimizer step
            # counter) comes back as a deleted buffer and poisons the
            # scope for the next step
            writes = set()
            for k in range(lo, hi):
                writes.update(ops[k].output_names())
            # the sentinel's fixed scan order over everything this
            # segment writes (outputs, grads, optimizer state)
            watch_names = sorted(writes)

            def seg_fn(donated, rest, base_key, step_idx, check=False):
                # python executes at trace time only: the counter is the
                # retrace probe the caching tests (and bench_dispatch's
                # sanity check) read
                self._trace_count += 1
                _m_retraces.inc()
                # constants enter via closure -> XLA compile-time consts
                env = dict(constants)
                env.update(rest)
                env.update(donated)
                env = _pin(env)
                if ad is None:
                    env = interpret(env, lo, hi, base_key, step_idx)
                else:
                    adop = ops[ad]
                    loss_name = adop.attrs["loss"]
                    param_names = adop.attrs["params"]
                    base = {k: v for k, v in env.items()
                            if k not in param_names}

                    def fwd(params):
                        e = dict(base)
                        e.update(params)
                        e = interpret(e, lo, ad, base_key, step_idx)
                        return jnp.sum(e[loss_name]), e

                    params = {n: env[n] for n in param_names}
                    (_, env2), grads = jax.value_and_grad(
                        fwd, has_aux=True)(params)
                    env = env2
                    for n in param_names:
                        g = grads[n]
                        t = _target(n + "@GRAD")
                        if t is not None:
                            # pin the gradient to its param's placement
                            # BEFORE the update ops consume it: the
                            # gradient collective then reduces the
                            # shard-local buffers where the sharded
                            # update needs them
                            from paddle_tpu.parallel._compat import \
                                sharding_constraint
                            g = sharding_constraint(g, spec.mesh, t)
                        env[n + "@GRAD"] = g
                    env = interpret(env, ad + 1, hi, base_key, step_idx)
                res = {k: v for k, v in env.items()
                       if k not in constants}
                res = _pin(res, state_default=True)
                if check:
                    # FLAGS_check_nan_inf: one fused isfinite reduction
                    # over every tensor this segment writes — a single
                    # extra scalar output, no extra dispatch
                    from paddle_tpu.monitor import numerics as _numerics
                    res[_SENTINEL_KEY] = _numerics.sentinel(
                        [env[n] for n in watch_names if n in env])
                return res

            fast = jax.jit(seg_fn, donate_argnums=(0,))
            # checked variant: separate jit (its own trace/compile,
            # first checked step pays it once), NO donation — the
            # localizer replays from the still-live pre-step state
            checked = jax.jit(
                lambda donated, rest, base_key, step_idx: seg_fn(
                    donated, rest, base_key, step_idx, True))
            return fast, checked, writes

        seg_fns = [None if is_host else make_device_fn(a, b)
                   for is_host, a, b in segs]

        return _CompiledStep(segs, seg_fns, constants, state_names,
                             fetch_names, interpret, ops)

    def _fetch_value(self, scope, name, return_numpy):
        v = scope.find_var(name)
        return np.asarray(v) if return_numpy and v is not None else v

    def close(self):
        self._cache.clear()
        self._runners.clear()


class AsyncExecutor:
    """async_executor.h:62 parity (the legacy pre-Trainer thread-pool
    trainer over DataFeed). On TPU the per-thread hogwild loops collapse
    into batched device steps, so this is a thin facade over
    Executor.train_from_dataset — kept because fluid user code
    instantiates fluid.AsyncExecutor(place) and calls run_from_files."""

    def __init__(self, place=None, run_mode=""):
        self._exe = Executor(place)

    def run(self, program, data_feed, filelist, thread_num, fetch,
            mode="", debug=False):
        data_feed.set_filelist(filelist)
        data_feed.set_thread(thread_num)
        return self._exe.train_from_dataset(
            program, data_feed,
            fetch_list=list(fetch) if fetch else None, debug=debug)

    run_from_files = run
