"""Nested sub-program control flow for STATIC programs.

Parity: the reference stores control-flow bodies as sub-BlockDescs
referenced from OpDesc BLOCK attrs (framework.proto:43 attr type BLOCK;
operators/controlflow/while_op.cc, recurrent_op.cc). The r2 build's
static mode had no serializable control flow — bodies were Python
callables, which cannot round-trip through a model file (VERDICT-r2
Weak #7 round-trip requirement).

TPU-first shape: a body callable is TRACED ONCE into a sub-Program
(symbolic Variables through the same layers ops as the parent), the op
carries the sub-Program in its attrs (structurally serializable,
static/serialize.py), and at execution the op's compute interprets the
sub-Program through the functional op registry inside lax.while_loop /
lax.scan — so the whole construct still compiles into the parent's one
XLA computation with structured control flow, no Python in the loop.

Variables the body closes over (parent params etc.) are detected as
captures and ride the op's input list, mirroring the reference's
sub-block outer-scope reads (while_op.cc kX inputs).
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import EnforceNotMet, enforce
from paddle_tpu.static.program import (
    OP_REGISTRY, Program, default_main_program, in_static_mode,
    program_guard,
)

__all__ = ["static_while_loop", "static_rnn_block", "trace_subprogram"]


def _static_shape(shape, name):
    enforce(shape is not None,
            f"control-flow var {name!r} has unknown shape")
    return tuple(2 if (s is None or s == -1) else int(s) for s in shape)


def _as_program_var(v, tag):
    """A loop/capture value may be a concrete array (e.g. a static-mode
    fill_constant with no tensor inputs evaluates eagerly): materialize
    it as a named constant of the parent program so the block op can
    reference it by name."""
    from paddle_tpu.framework import unique_name
    if hasattr(v, "name") and hasattr(v, "block"):
        return v
    arr = jnp.asarray(v)
    program = default_main_program()
    blk = program.global_block()
    name = unique_name.generate(f"const_{tag}")
    nv = blk.create_var(name=name, shape=arr.shape, dtype=arr.dtype)
    if not hasattr(program, "_constants"):
        program._constants = {}
    program._constants[name] = arr
    return nv


def trace_subprogram(fn, input_vars, input_shapes=None):
    """Trace ``fn`` (taking len(input_vars) symbolic Variables) into a
    fresh sub-Program. Returns (sub_program, in_names, out_names,
    captured_names).

    ``input_shapes`` overrides the per-input shapes (e.g. a scan body
    sees one time-slice of a sequence input)."""
    from paddle_tpu.framework import unique_name

    sub = Program()
    startup = Program()   # throwaway; body fns must not create params
    in_names, sym = [], []
    with program_guard(sub, startup), unique_name.guard():
        blk = sub.global_block()
        for i, v in enumerate(input_vars):
            shape = (input_shapes[i] if input_shapes is not None
                     else v.shape)
            nv = blk.create_var(name=f"@in@{i}@{v.name}", shape=shape,
                                dtype=v.dtype, is_data=True)
            in_names.append(nv.name)
            sym.append(nv)
        outs = fn(*sym)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    out_names = []
    for o in outs:
        enforce(hasattr(o, "name"),
                "control-flow body must return program Variables "
                "(build outputs with layers ops)")
        out_names.append(o.name)
    enforce(not startup.global_block().ops,
            "control-flow bodies must not create parameters — close "
            "over parent parameters instead (they become captures)")
    # captures: names referenced by sub ops but defined nowhere inside
    defined = set(blk.vars) | set(getattr(sub, "_constants", {}))
    captured = []
    for op in blk.ops:
        enforce(not op.attrs.get("_host"),
                f"host op {op.type!r} inside a control-flow body")
        enforce(not op.attrs.get("_needs_rng"),
                f"rng op {op.type!r} inside a control-flow body is not "
                f"supported yet (hoist randomness out of the loop)")
        for n in op.input_names():
            if n not in defined and n not in captured:
                captured.append(n)
    return sub, in_names, out_names, captured


def _run_subprogram(prog, in_names, in_vals, captured, cap_vals,
                    out_names):
    """Interpret a sub-Program functionally: env in -> outputs."""
    from paddle_tpu.static.executor import exec_op
    env = dict(getattr(prog, "_constants", {}))
    env.update(zip(in_names, in_vals))
    env.update(zip(captured, cap_vals))
    for op in prog.global_block().ops:
        env.update(exec_op(op, env, None))
    return [env[n] for n in out_names]


# ---------------------------------------------------------------------------
# while_block
# ---------------------------------------------------------------------------
def _while_block_compute(ins, attrs):
    n_loop = attrs["n_loop"]
    vals = list(ins["X"])
    loop_vals, cap_vals = vals[:n_loop], vals[n_loop:]
    cond_p, body_p = attrs["cond_program"], attrs["body_program"]
    captured = attrs["captured"]

    def cond(vs):
        out = _run_subprogram(cond_p, attrs["cond_in"], list(vs),
                              captured, cap_vals, attrs["cond_out"])
        return jnp.reshape(out[0], ())

    def body(vs):
        return tuple(_run_subprogram(body_p, attrs["body_in"], list(vs),
                                     captured, cap_vals,
                                     attrs["body_out"]))

    out = jax.lax.while_loop(cond, body, tuple(loop_vals))
    return {"Out": list(out)}


OP_REGISTRY["while_block"] = _while_block_compute


def static_while_loop(cond_fn, body_fn, loop_vars):
    """Static-mode layers.while_loop (ref layers/control_flow.py:630
    While + while_op.cc): bodies traced to sub-programs held in op attrs
    so the program serializes; lowers to lax.while_loop at execution."""
    enforce(in_static_mode(), "static_while_loop requires static mode")
    single = not isinstance(loop_vars, (tuple, list))
    lvars = [loop_vars] if single else list(loop_vars)
    lvars = [_as_program_var(v, "while_in") for v in lvars]

    cond_p, cond_in, cond_out, cap_c = trace_subprogram(cond_fn, lvars)
    enforce(len(cond_out) == 1, "while cond must return one boolean")
    body_p, body_in, body_out, cap_b = trace_subprogram(body_fn, lvars)
    enforce(len(body_out) == len(lvars),
            f"while body returned {len(body_out)} vars for "
            f"{len(lvars)} loop vars")
    captured = list(dict.fromkeys(cap_c + cap_b))

    blk = default_main_program().global_block()
    outs = [blk.create_var(shape=v.shape, dtype=v.dtype) for v in lvars]
    blk.append_op(
        type="while_block",
        inputs={"X": [v.name for v in lvars] + captured},
        outputs={"Out": [o.name for o in outs]},
        attrs={"n_loop": len(lvars), "captured": captured,
               "cond_program": cond_p, "cond_in": cond_in,
               "cond_out": cond_out, "body_program": body_p,
               "body_in": body_in, "body_out": body_out})
    return outs[0] if single else outs


# ---------------------------------------------------------------------------
# scan_block (StaticRNN)
# ---------------------------------------------------------------------------
def _scan_block_compute(ins, attrs):
    vals = list(ins["X"])
    seq, mem = vals[0], vals[1]
    cap_vals = vals[2:]
    body_p, captured = attrs["body_program"], attrs["captured"]

    xs = jnp.moveaxis(seq, 1, 0)                      # time-major

    def body(carry, x_t):
        new_mem, out_t = _run_subprogram(
            body_p, attrs["body_in"], [carry, x_t],
            captured, cap_vals, attrs["body_out"])
        return new_mem, out_t

    final, outs = jax.lax.scan(body, mem, xs)
    return {"Out": [final, jnp.moveaxis(outs, 0, 1)]}


OP_REGISTRY["scan_block"] = _scan_block_compute


def static_rnn_block(step_fn, inputs, initial_state):
    """Static-mode StaticRNN (ref layers/control_flow.py:280 +
    recurrent_op.cc), same surface as the eager static_rnn:
    ``inputs`` is a [B, T, ...] Variable, ``initial_state`` a [B, ...]
    Variable, and step_fn(state, x_t) -> (new_state, out_t) built from
    layers ops. Returns (final_state, outs[B, T, ...]) Variables. The
    step body is a serializable sub-program; lowers to lax.scan
    (differentiable, so append_backward sees through it)."""
    enforce(in_static_mode(), "static_rnn_block requires static mode")
    seq, mem = inputs, initial_state
    enforce(seq.shape is not None and len(seq.shape) >= 2,
            "sequence input must be [B, T, ...]")
    slice_shape = (seq.shape[0],) + tuple(seq.shape[2:])

    body_p, body_in, body_out, captured = trace_subprogram(
        lambda m, x_t: step_fn(m, x_t),
        [mem, seq], input_shapes=[mem.shape, slice_shape])
    enforce(len(body_out) == 2,
            "step_fn must return (new_state, out_t)")

    # infer out_t's shape by shape-evaluating the sub-program
    blk = default_main_program().global_block()
    T = seq.shape[1]
    cap_specs = [jax.ShapeDtypeStruct(
        _static_shape(blk.var(n).shape, n), blk.var(n).dtype)
        for n in captured]
    in_specs = [jax.ShapeDtypeStruct(_static_shape(mem.shape, "state"),
                                     mem.dtype),
                jax.ShapeDtypeStruct(_static_shape(slice_shape, "x_t"),
                                     seq.dtype)]

    def probe(m, x_t, *caps):
        return _run_subprogram(body_p, body_in, [m, x_t],
                               captured, list(caps), body_out)

    st_spec, out_spec = jax.eval_shape(probe, *(in_specs + cap_specs))

    final = blk.create_var(shape=mem.shape, dtype=st_spec.dtype)
    # a dynamic batch dim (-1/None) was probed with a placeholder (2):
    # propagate the DECLARED marker, not the probe value, whenever the
    # body preserved the batch extent
    batch = seq.shape[0]
    probed_batch = _static_shape(slice_shape, "x_t")[0]
    out_batch = (batch if out_spec.shape[0] == probed_batch
                 else out_spec.shape[0])
    out = blk.create_var(
        shape=(out_batch, T) + tuple(out_spec.shape[1:]),
        dtype=out_spec.dtype)
    blk.append_op(
        type="scan_block",
        inputs={"X": [seq.name, mem.name] + captured},
        outputs={"Out": [final.name, out.name]},
        attrs={"captured": captured, "body_program": body_p,
               "body_in": body_in, "body_out": body_out})
    return final, out
