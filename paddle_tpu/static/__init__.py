"""Static-graph (Program) subsystem.

Parity target: the reference's core identity — ProgramDesc/BlockDesc/OpDesc
(ref: paddle/fluid/framework/framework.proto:43-188, python framework.py
Program:2775/Block:1436/Operator:985) plus Executor
(ref: python executor.py:294, C++ framework/executor.cc).

TPU-native redesign: a Program is still a serializable op-list IR (so
save/load/prune/inference parity holds), but execution is NOT an op-by-op
interpreter (ref hot loop: executor.cc:417-421). The Executor traces the
whole block through the functional op registry and compiles it with
`jax.jit` into ONE XLA computation; parameters and optimizer state live in
a Scope carried across steps as a donated pytree.
"""

from paddle_tpu.static.program import (
    Program, Block, Operator, Variable, Parameter, program_guard,
    default_main_program, default_startup_program, name_scope,
    OP_REGISTRY, register_op, in_static_mode, static_mode_guard, data,
    enable_static, disable_static,
)
from paddle_tpu.static.executor import (
    AsyncExecutor, Executor, Scope, device_prefetch, global_scope,
    scope_guard,
)
from paddle_tpu.static.debugger import pprint_program, draw_graph, memory_usage
# registers the while_block/scan_block computes in OP_REGISTRY — a
# deserialized program must execute without the builder APIs having run
import paddle_tpu.static.nested  # noqa: F401
# registers the fused_matmul compute — a program optimized/quantized in
# another process (AOT export, quantized serving) must execute without
# the pass pipeline having run here
import paddle_tpu.static.opt_passes  # noqa: F401
from paddle_tpu.static.backward import append_backward, gradients
from paddle_tpu.static.io import (
    save_inference_model, load_inference_model, save_params,
    load_params, save_persistables, load_persistables,
    append_save_op, append_load_op,
)

from paddle_tpu.compiler import (            # noqa: E402,F401
    CompiledProgram, ExecutionStrategy, BuildStrategy,
)
