"""Program introspection: pretty printer, graphviz export, memory calc.

Parity targets: python/paddle/fluid/debugger.py (draw_block_graphviz,
pprint_program_codes), net_drawer.py / graphviz.py (op graph rendering),
the ir graph_viz_pass.cc (dot export of the IR graph), and
contrib/memory_usage_calc.py (per-program activation memory estimate).

The dot output needs no graphviz binding — it is plain text a user feeds
to `dot -Tpng`; vars are ellipses, ops are boxes, params are doubled
ellipses (the reference's shapes).
"""

import numpy as np

from paddle_tpu.core.dtypes import numpy_dtype
from paddle_tpu.static.program import Parameter, default_main_program

__all__ = ["pprint_program", "draw_graph", "memory_usage"]


def _fmt_shape(shape):
    return "x".join("?" if s in (None, -1) else str(s)
                    for s in (shape or ()))


def pprint_program(program=None, show_vars=True):
    """debugger.pprint_program_codes parity: a readable dump of every
    block's vars and ops. Returns the string (and prints nothing)."""
    program = program or default_main_program()
    lines = []
    for blk in program.blocks:
        lines.append(f"-- block {blk.idx} (parent {blk.parent_idx}) --")
        if show_vars:
            for name, v in sorted(blk.vars.items()):
                kind = ("param" if isinstance(v, Parameter)
                        else "data" if getattr(v, "is_data", False)
                        else "var")
                persist = " persistable" if getattr(v, "persistable",
                                                    False) else ""
                lines.append(f"  {kind:6s} {name}: "
                             f"{_fmt_shape(v.shape)} {v.dtype}{persist}")
        for i, op in enumerate(blk.ops):
            ins = ", ".join(f"{k}={v}" for k, v in op.inputs.items())
            outs = ", ".join(f"{k}={v}" for k, v in op.outputs.items())
            lines.append(f"  [{i:3d}] {op.type}({ins}) -> {outs}")
    return "\n".join(lines)


def draw_graph(program=None, path=None, graph_name="program"):
    """Graphviz dot source for the op/var dependency graph
    (draw_block_graphviz / graph_viz_pass.cc parity). Writes to ``path``
    if given; always returns the dot text."""
    program = program or default_main_program()
    blk = program.global_block()
    out = [f"digraph {graph_name} {{", "  rankdir=TB;"]

    def vid(name):
        return f'var_{name}'.replace(".", "_").replace("@", "_AT_")

    drawn = set()

    def draw_var(name):
        if name in drawn:
            return
        drawn.add(name)
        v = blk.vars.get(name)
        if isinstance(v, Parameter):
            style = 'shape=ellipse, peripheries=2, color=darkgreen'
        elif v is not None and getattr(v, "is_data", False):
            style = 'shape=ellipse, color=blue'
        else:
            style = 'shape=ellipse'
        label = name if v is None else f"{name}\\n{_fmt_shape(v.shape)}"
        out.append(f'  {vid(name)} [label="{label}", {style}];')

    for i, op in enumerate(blk.ops):
        oid = f"op_{i}"
        out.append(f'  {oid} [label="{op.type}", shape=box, '
                   f'style=filled, fillcolor=lightgrey];')
        for name in op.input_names():
            draw_var(name)
            out.append(f"  {vid(name)} -> {oid};")
        for name in op.output_names():
            draw_var(name)
            out.append(f"  {oid} -> {vid(name)};")
    out.append("}")
    text = "\n".join(out)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def memory_usage(program=None, batch_size=1):
    """contrib/memory_usage_calc.py parity: lower/upper estimate (bytes)
    of the program's tensor footprint at the given batch size. The -1/None
    leading dim is read as the batch dimension."""
    program = program or default_main_program()
    total = 0
    for blk in program.blocks:
        for v in blk.vars.values():
            if not v.shape:
                continue
            n = 1
            for s in v.shape:
                n *= batch_size if s in (None, -1) else int(s)
            try:
                total += n * numpy_dtype(v.dtype).itemsize
            except (TypeError, ValueError):
                total += n * 4
    # the reference reports a +/-30% band (memory_usage_calc.py does the
    # same: activation reuse vs gradient doubling are unknowable pre-run)
    return int(total * 0.7), int(total * 1.3)
