"""Framework-level user helpers.

Parity targets: python/paddle/fluid/framework.py (unique_name, ParamAttr
from param_attr.py, Variable), dygraph base (to_variable, no_grad
ref: python/paddle/fluid/dygraph/base.py).
"""

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "unique_name", "ParamAttr", "WeightNormParamAttr", "Variable",
    "to_variable", "no_grad", "grad", "stop_gradient",
]

_uid = threading.local()


class _UniqueNameGenerator:
    """python/paddle/fluid/unique_name.py parity."""

    def __init__(self):
        self.ids = {}

    def __call__(self, prefix):
        n = self.ids.get(prefix, 0)
        self.ids[prefix] = n + 1
        return f"{prefix}_{n}" if n else prefix

    def reset(self):
        self.ids = {}


class _UniqueNameModule:
    def __init__(self):
        self._gen = _UniqueNameGenerator()

    def generate(self, prefix):
        return self._gen(prefix)

    def reset(self):
        self._gen.reset()

    @contextlib.contextmanager
    def guard(self):
        old = self._gen
        self._gen = _UniqueNameGenerator()
        try:
            yield
        finally:
            self._gen = old

    def switch(self, new_generator=None):
        """fluid.unique_name.switch parity: swap the generator and
        return the previous one (pair with a later switch(old))."""
        old = self._gen
        self._gen = new_generator or _UniqueNameGenerator()
        return old


unique_name = _UniqueNameModule()


class ParamAttr:
    """python/paddle/fluid/param_attr.py parity."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average

    @staticmethod
    def to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if arg is False:
            return None
        # an initializer instance
        return ParamAttr(initializer=arg)


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


# Variable is the static-graph symbolic tensor; defined in static.program,
# re-exported here for fluid.framework parity.
from paddle_tpu.static.program import Variable  # noqa: E402


def to_variable(value, name=None, zero_copy=None):
    """dygraph.to_variable parity: host array → device array (eager)."""
    if isinstance(value, jnp.ndarray):
        return value
    return jnp.asarray(np.asarray(value))


_no_grad_state = threading.local()


def in_no_grad():
    """True inside a ``no_grad()`` region (thread-local)."""
    return getattr(_no_grad_state, "depth", 0) > 0


class _NoGrad:
    """dygraph.no_grad parity (ref python/paddle/fluid/dygraph/base.py).

    Real semantics, not a no-op: inside the region every ``nn.Layer``
    call wraps its outputs in ``lax.stop_gradient``, so parameters used
    only under ``no_grad`` receive exactly-zero gradients. Works as a
    context manager and as a decorator (both forms exist in the
    reference). Raw jnp math outside any Layer is functional and cannot
    be intercepted — wrap such code with ``stop_gradient`` explicitly.

    TRACE-TIME semantics (like every Python-level flag under jit): the
    flag is read while a function is being traced and is baked into the
    compiled computation; it is NOT part of jax.jit's cache key. Do not
    call one jitted function both inside and outside a ``no_grad``
    region — whichever call traces first wins for all later cached
    calls. Enter ``no_grad`` inside the function being jitted (or use
    separate jitted callables for frozen/unfrozen passes), exactly as
    with flax-style ``deterministic`` flags.
    """

    def __enter__(self):
        _no_grad_state.depth = getattr(_no_grad_state, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _no_grad_state.depth -= 1
        return False

    def __call__(self, fn=None):
        if fn is None:           # ``with no_grad():`` form
            return self

        @functools.wraps(fn)     # ``@no_grad`` decorator form
        def inner(*a, **k):
            with self:
                return jax.tree.map(jax.lax.stop_gradient, fn(*a, **k))
        return inner


no_grad = _NoGrad()


def stop_gradient(x):
    return jax.lax.stop_gradient(x)


def grad(fn, argnums=0, has_aux=False):
    """Expose JAX autodiff under the framework namespace."""
    return jax.grad(fn, argnums=argnums, has_aux=has_aux)
