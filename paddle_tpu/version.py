"""Version metadata (the fluid/framework commit-stamp analog —
paddle/fluid/platform/init.cc prints its own; tools/print_signatures
freezes the API per version)."""

__version__ = "0.4.0"          # bumped per build round

full_version = __version__
major, minor, patch = (int(x) for x in __version__.split("."))


def show():
    """paddle.version.show() parity."""
    print(f"paddle-tpu {__version__}")
