"""fluid.compiler parity — CompiledProgram.with_data_parallel.

Parity: python/paddle/fluid/compiler.py (CompiledProgram:48,
with_data_parallel:116) + the strategy structs crossing pybind
(details/execution_strategy.h:22, details/build_strategy.h:36).

TPU-native redesign (SURVEY §3.2, the north-star path): the reference
clones the graph per device and inserts NCCL allreduce per gradient
(multi_devices_graph_pass.cc). Here the SAME single-program step the
Executor already compiles is partitioned by GSPMD: feed arrays are
sharded over the "data" mesh axis (batch dim), persistable state stays
replicated, and XLA inserts the gradient all-reduce where the batch-mean
loss meets the replicated parameters — no graph rewrite, no per-gradient
plumbing. `exe.run(compiled_program, ...)` is the same call as the
reference.
"""

from enum import Enum

from paddle_tpu.core.enforce import EnforceNotMet

__all__ = ["CompiledProgram", "ExecutionStrategy", "BuildStrategy",
           "ReduceStrategy"]


class ReduceStrategy(Enum):
    """build_strategy.h:38-57. AllReduce replicates params; Reduce is
    realized as the ZeRO-style sharded layout (the functional trainer
    consumes it via DataParallelTrainer(param_sharding="reduce"); the
    static path trains AllReduce-style either way — XLA's partitioner
    owns placement)."""
    AllReduce = 0
    Reduce = 1


class ExecutionStrategy:
    """execution_strategy.h:22 — thread/scope knobs for the SSA
    executors. XLA owns scheduling and buffer lifetime, so these are
    recorded for API compatibility and inspection only."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.allow_op_delay = False
        self.use_thread_barrier = True


class BuildStrategy:
    """build_strategy.h:36 — multi-device graph-build knobs. The rows
    XLA subsumes (fusion, memory planning, inplace) are recorded only;
    reduce_strategy maps to the ZeRO layout on the functional path
    (fleet.DistributedStrategy.param_sharding_arg) and
    gradient_scale_strategy is honored by the batch-mean loss
    convention (scale 1/N == averaging over the full global batch)."""

    ReduceStrategy = ReduceStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = "CoeffNumDevice"
        # program-level optimization pass pipeline
        # (static/opt_passes.py): None = inherit FLAGS_apply_ir_passes
        # (on by default); True/False pin it for THIS program —
        # False is the bit-identical legacy lowering, the A/B lever
        # `bench.py passes` measures against (docs/PERFORMANCE.md
        # "Program pass pipeline")
        self.apply_ir_passes = None
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.memory_optimize = None
        self.enable_inplace = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True


class CompiledProgram:
    """compiler.py CompiledProgram parity. Wrap a Program; after
    ``with_data_parallel()`` the Executor runs its one fused XLA step
    SPMD over the data mesh (feeds batch-sharded, state replicated).
    Without it, behaves exactly like the wrapped program."""

    def __init__(self, program, build_strategy=None):
        from paddle_tpu.static.program import Program
        if isinstance(program, CompiledProgram):
            raise EnforceNotMet("program is already a CompiledProgram")
        if not isinstance(program, Program):
            raise EnforceNotMet(
                f"CompiledProgram wraps a Program, got {type(program)}")
        self._program = program
        self._build_strategy = build_strategy
        self._exec_strategy = None
        self._mesh = None
        # the ONE parallel-mode switch: the executor branches on _spec
        # (with_data_parallel sets the trivial pure-DP spec, so both
        # entry points leave a consistent state — no separate _dp flag
        # to drift out of sync)
        self._spec = None
        self._loss_name = None

    def with_mesh_sharding(self, spec=None, loss_name=None):
        """Unified mesh partitioner entry (ROADMAP item 2): attach a
        ``parallel.spec.ShardingSpec`` so the Executor places this
        program's persistable state per the spec's per-param
        PartitionSpecs, shards feeds per its batch-axis specs, and pins
        the spec'd names inside every compiled device segment with
        ``with_sharding_constraint`` — pjit in/out shardings end to
        end, one annotation source for data/model/pipe placement.
        ``with_data_parallel`` is the pure-DP special case (it builds a
        default spec internally)."""
        from paddle_tpu.parallel.spec import ShardingSpec
        if spec is None:
            spec = ShardingSpec()
        if not isinstance(spec, ShardingSpec):
            raise EnforceNotMet(
                f"with_mesh_sharding expects a parallel.ShardingSpec, "
                f"got {type(spec).__name__}")
        self._spec = spec
        self._mesh = spec.mesh
        self._loss_name = (loss_name if isinstance(loss_name, str)
                           or loss_name is None else loss_name.name)
        return self

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """compiler.py:116 parity. places: a device list or count; the
        default is every visible device on one "data" mesh axis."""
        import jax
        from paddle_tpu.parallel.mesh import MeshConfig, make_mesh
        self._loss_name = (loss_name if isinstance(loss_name, str)
                           or loss_name is None else loss_name.name)
        self._build_strategy = build_strategy or self._build_strategy \
            or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        if places is None:
            devices = jax.devices()
        elif isinstance(places, int):
            devices = jax.devices()[:places]
        else:
            devices = [p.jax_device() if hasattr(p, "jax_device") else p
                       for p in places]
        self._mesh = make_mesh(MeshConfig(data=len(devices)),
                               devices=devices)
        # pure DP is the trivial ShardingSpec: params replicated, feeds
        # batch-sharded over "data" — the executor consumes ONLY the
        # spec, so this path and with_mesh_sharding share every line of
        # the placement/lowering machinery
        from paddle_tpu.parallel.spec import ShardingSpec
        self._spec = ShardingSpec(self._mesh)
        return self

    # the Executor reads program attributes through the wrapper
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.__dict__["_program"], name)
