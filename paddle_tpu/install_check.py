"""fluid.install_check parity: `paddle_tpu.install_check.run_check()`
trains a tiny linear model end-to-end (single device, then data-parallel
over every visible device) and prints the verdict — the reference's
post-install sanity ritual (python/paddle/fluid/install_check.py)."""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["run_check"]


def _train_once(devices):
    import paddle_tpu as pt
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=len(devices)), devices=devices)
    rng = np.random.RandomState(0)
    x = rng.rand(8 * len(devices), 4).astype(np.float32)
    y = (x @ np.linspace(-1, 1, 4)).astype(np.float32)[:, None]
    # batch sharded over the data axis, params replicated: the loss mean
    # forces a cross-device reduction, so every device and the collective
    # path genuinely participate
    dsh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    xs = jax.device_put(x, dsh)
    ys = jax.device_put(y, dsh)

    def loss_fn(params, xb, yb):
        pred = xb @ params["w"] + params["b"]
        return jnp.mean((pred - yb) ** 2)

    params = jax.device_put({"w": jnp.zeros((4, 1)),
                             "b": jnp.zeros((1,))}, rep)
    opt = pt.optimizer.SGDOptimizer(0.1)
    state = opt.init(params)
    step = jax.jit(lambda p, s, xb, yb: (
        lambda g: opt.apply_gradients(p, g, s))(
            jax.grad(loss_fn)(p, xb, yb)))
    loss_jit = jax.jit(loss_fn)     # eval under jit too: eager compute
    first = float(loss_jit(params, xs, ys))  # on sharded arrays is not
    for _ in range(40):                      # supported on all backends
        params, state = step(params, state, xs, ys)
    return first, float(loss_jit(params, xs, ys))


def run_check():
    devices = jax.devices()
    print(f"Running install check on {len(devices)} "
          f"{devices[0].platform} device(s)...")
    f1, l1 = _train_once(devices[:1])
    if not l1 < f1:        # real exception, not assert: must survive -O
        raise RuntimeError(
            f"single-device training did not converge ({f1} -> {l1})")
    print("  single device: OK")
    if len(devices) > 1:
        f2, l2 = _train_once(devices)
        if not l2 < f2:
            raise RuntimeError(
                f"multi-device training did not converge ({f2} -> {l2})")
        print(f"  data parallel x{len(devices)}: OK")
    print("Your paddle_tpu install works! Training converges; you can "
          "now build models.")
