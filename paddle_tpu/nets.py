"""fluid.nets parity: composite network helpers.

Rebuild of python/paddle/fluid/nets.py (simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention)
composed from paddle_tpu.layers primitives. On TPU these compose into a
single XLA computation — the reference's per-op dispatch disappears.
"""

import jax.numpy as jnp

from paddle_tpu import layers

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "sequence_conv_pool", "glu",
    "scaled_dot_product_attention",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """nets.simple_img_conv_pool parity (ref: python/paddle/fluid/nets.py)."""
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act, use_cudnn=use_cudnn)
    return layers.pool2d(
        conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """nets.img_conv_group parity: conv(+bn+dropout)* then one pool."""
    tmp = input
    if not hasattr(conv_num_filter, "__len__"):
        conv_num_filter = [conv_num_filter]

    def _expand(v):
        return v if hasattr(v, "__len__") else [v] * len(conv_num_filter)

    padding = _expand(conv_padding)
    fsize = _expand(conv_filter_size)
    with_bn = _expand(conv_with_batchnorm)
    drop = _expand(conv_batchnorm_drop_rate)
    pattr = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(conv_num_filter)

    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not with_bn[i] else None
        tmp = layers.conv2d(
            input=tmp, num_filters=nf, filter_size=fsize[i],
            padding=padding[i], param_attr=pattr[i],
            act=local_act, use_cudnn=use_cudnn)
        if with_bn[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if abs(drop[i]) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop[i])

    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    """nets.sequence_conv_pool parity: context conv then sequence pool.

    ``input``: RaggedBatch / (data [B, T, H], lengths)."""
    from paddle_tpu.core.lod import RaggedBatch
    from paddle_tpu.framework import ParamAttr
    from paddle_tpu import initializer as I
    from paddle_tpu.layers import _make_param, _apply_act
    from paddle_tpu.ops import sequence as seq_ops

    data = input.data if isinstance(input, RaggedBatch) else input[0]
    h = int(data.shape[-1])
    w = _make_param("seqconv_w", (filter_size * h, num_filters),
                    jnp.float32, param_attr, I.Xavier())
    conv_out = seq_ops.sequence_conv(input, w, filter_size)
    if bias_attr is not False:
        b = _make_param("seqconv_b", (num_filters,), jnp.float32, bias_attr,
                        I.Constant(0.0))
        conv_out = RaggedBatch(conv_out.data + b, conv_out.lengths)
    conv_out = RaggedBatch(_apply_act(conv_out.data, act), conv_out.lengths)
    return seq_ops.sequence_pool(conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    """nets.glu parity: a, b = split(x); a * sigmoid(b)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """nets.scaled_dot_product_attention parity: multi-head attention from
    primitive ops. [B, T, D] inputs; returns [B, Tq, Dv]. On TPU the
    softmax(QK^T)V chain fuses in XLA; see ops/pallas for the flash
    kernel used by the model zoo."""
    q, k, v = (jnp.asarray(x) for x in (queries, keys, values))
    b, tq, d = q.shape
    dv = v.shape[-1]
    if d % num_heads or dv % num_heads:
        raise ValueError("hidden size must divide num_heads")

    def split_heads(x):
        bb, tt, dd = x.shape
        return jnp.transpose(
            x.reshape(bb, tt, num_heads, dd // num_heads), (0, 2, 1, 3))

    qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
    scale = (d // num_heads) ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", weights, vh)
    ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(b, tq, dv)
    return ctx
