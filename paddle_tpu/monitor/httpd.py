"""Shared threaded-HTTP plumbing: the lifecycle base under both the
metrics endpoint (``exporter.MetricsServer``) and the serving front
door (``serving/frontdoor.py``).

Both servers want the exact same shell — stdlib
``http.server.ThreadingHTTPServer`` on a daemon thread, ``port=0``
free-port pick, loopback-only default, bounded ``stop()``, and a
per-connection socket timeout so one stalled peer (a wedged scraper, a
slow-loris client) can never pin a handler thread forever. What
differs is only the handler, so subclasses supply exactly that via
:meth:`_handler_class` and inherit the rest.

The timeout rides stdlib mechanics: ``BaseHTTPRequestHandler.timeout``
makes ``setup()`` call ``connection.settimeout()``, so EVERY blocking
socket read/write in the handler — request line, headers, body, the
response write — is bounded. A timeout while *waiting between*
requests on a keep-alive connection just closes it (handled inside
``handle_one_request``); a timeout *mid-request* surfaces to the
handler, which can answer with a typed status before closing.
"""

import http.server
import threading

from paddle_tpu.core.enforce import enforce

__all__ = ["ThreadedHTTPServerBase"]


class ThreadedHTTPServerBase:
    """Lifecycle shell for a threaded stdlib HTTP server.

    Subclasses implement ``_handler_class() -> BaseHTTPRequestHandler
    subclass``; the base wires the per-connection ``timeout`` and
    ``protocol_version`` class attributes onto it, binds the listener
    (``port=0`` picks a free port — read ``self.port`` after
    ``start()``), and runs ``serve_forever`` on a daemon thread.
    Loopback-only by default: both users of this base (metrics, the
    serving front door) expose process internals, so listening beyond
    the host is an explicit choice.

    ``socket_timeout_s`` bounds every blocking socket operation of
    every connection (None disables — not recommended; it restores
    the pin-a-thread-forever failure mode this base exists to close).
    """

    #: daemon-thread name, for operator-facing thread dumps
    thread_name = "pt-httpd"
    #: HTTP/1.1 so keep-alive works; requires every response to carry
    #: Content-Length (both subclasses do)
    protocol_version = "HTTP/1.1"

    def __init__(self, port=0, host="127.0.0.1", socket_timeout_s=10.0):
        enforce(socket_timeout_s is None or float(socket_timeout_s) > 0,
                f"socket_timeout_s must be > 0 or None, got "
                f"{socket_timeout_s!r}")
        self.host = host
        self.port = port
        self.socket_timeout_s = None if socket_timeout_s is None \
            else float(socket_timeout_s)
        self._httpd = None
        self._thread = None

    def _handler_class(self):
        raise NotImplementedError(
            "ThreadedHTTPServerBase subclasses supply the handler")

    @property
    def running(self):
        return self._httpd is not None

    def start(self):
        handler = self._handler_class()
        # class attrs, not instance: http.server instantiates the
        # handler itself, one per connection
        handler.timeout = self.socket_timeout_s
        handler.protocol_version = self.protocol_version
        # headers and body flush as separate segments; with Nagle on,
        # the body then waits out the peer's delayed ACK (~40ms flat
        # per response on loopback) — TCP_NODELAY, always
        handler.disable_nagle_algorithm = True
        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=self.thread_name)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
