"""Training-health anomaly detection, postmortem triggering, and
straggler readout.

PR 3's telemetry answers "what are the numbers"; this module answers
"has the run gone wrong" — and makes sure the evidence survives. Four
anomaly kinds, matching how training runs actually die:

- ``non_finite`` — a nan/inf tensor (tripped by the in-graph sentinels
  in ``monitor.numerics``, or any non-finite signal — loss, grad norm
  — fed to the detector, so a NaN run is caught even without
  ``FLAGS_check_nan_inf``'s memory cost);
- ``loss_spike`` — loss jumps far above its trailing-window median;
- ``grad_explosion`` — gradient global norm (from
  ``monitor.tensorwatch``) jumps far above its trailing median;
- ``step_stall`` — wall step time (fed by ``Executor.run``) jumps far
  above its trailing median.

On a trip: the ``anomaly_trips_total{kind}`` counter moves,
``train_health`` drops to 0 (exported in this rank's ``.prom``
snapshot, so the launcher-side job view sees it), the flight recorder
gets a note, and — once per kind per process, so a persisting
condition cannot spam the disk — the recorder dumps a postmortem JSON
(``rank<R>.<pid>.anomaly-<kind>.json``) with the anomaly named under
an ``"anomaly"`` key. Everything is opt-in: ``enable()`` arms the
detector (the executor and tensorwatch check one module bool before
feeding it), while ``trip()`` itself always works — the numerics
sentinel uses it even when the windowed detector is off, because
``FLAGS_check_nan_inf`` was its own opt-in.

The launcher side (stdlib-only, like everything in this module):
``straggler_ranks`` and ``job_health`` read the per-rank ``.prom``
snapshots the exporter already aggregates and derive the ``health=``
field of the status line — a rank whose mean ``executor_step_ms``
sits far above the median rank's is a straggler (the data-parallel
gang runs at its pace), and any rank whose snapshot carries trips or
``train_health 0`` marks the job anomalous.

Docs: docs/DEBUGGING.md (detector + postmortems),
docs/OBSERVABILITY.md (metric catalogue entries).
"""

import collections
import statistics
import threading

from paddle_tpu.monitor import flight_recorder as _flight
from paddle_tpu.monitor.registry import counter, gauge

__all__ = [
    "AnomalyDetector", "DETECTOR", "enable", "disable", "is_enabled",
    "trip", "straggler_ranks", "job_health", "KINDS",
]

KINDS = ("non_finite", "loss_spike", "grad_explosion", "step_stall")

_m_trips = counter(
    "anomaly_trips_total",
    "Anomaly-detector trips by kind (non_finite, loss_spike, "
    "grad_explosion, step_stall)", labels=("kind",))
_g_health = gauge(
    "train_health",
    "1 while no anomaly has tripped in this process, 0 after any trip "
    "(set to 1 by anomaly.enable())")
_g_last_step = gauge(
    "last_anomaly_step",
    "Step index of this process's most recent anomaly trip")

#: instrumented hot paths read this bool directly (the
#: flight_recorder._enabled pattern) before touching the detector
_enabled = False

_trip_lock = threading.Lock()
_dumped_kinds = set()


def trip(kind, report=None, step=None):
    """Register one anomaly: count it, drop ``train_health``, note it
    to the flight recorder, and — first trip of this kind in this
    process only — dump a postmortem JSON with the anomaly named.
    Returns the dump path (or None: recorder unarmed / repeat kind).
    Works whether or not the windowed detector is enabled."""
    _m_trips.inc(kind=kind)
    _g_health.set(0.0)
    if step is not None:
        _g_last_step.set(step)
    if _flight._enabled:
        _flight.RECORDER.note("anomaly", kind, step=step)
    with _trip_lock:
        first = kind not in _dumped_kinds
        _dumped_kinds.add(kind)
    if not first:
        return None
    doc = dict(report or {})
    doc["kind"] = kind
    if step is not None:
        doc.setdefault("step", step)
    # the tripping thread's in-flight span tree rides the dump's own
    # top-level "trace" embed (flight_recorder.dump) — the postmortem
    # names the PHASE the step died in (dispatch vs fetch vs
    # feed_stage), not just the step number
    return _flight.RECORDER.dump(reason=f"anomaly-{kind}",
                                 extra={"anomaly": doc})


class AnomalyDetector:
    """Windowed host-side detector. Feed it whatever the loop has —
    ``observe(step=, loss=, grad_norm=, step_ms=)``, every argument
    optional — and it trips when a value jumps ``factor``× above the
    trailing-window median (median, not mean — and breaching values
    never join the window, so an anomaly cannot drag its own baseline
    up). ``step_stall`` additionally requires ``stall_consecutive``
    breaching steps in a row: a stall is sustained by definition, and
    a single scheduler hiccup on a shared host must not page anyone.
    A tripped kind cools down for ``cooldown`` observations so an
    ongoing condition counts once per cooldown, not once per step."""

    def __init__(self, window=64, min_samples=8, loss_spike_factor=4.0,
                 grad_explosion_factor=10.0, stall_factor=10.0,
                 stall_consecutive=3, cooldown=100):
        self.min_samples = int(min_samples)
        self.cooldown = int(cooldown)
        self._factors = {"loss_spike": float(loss_spike_factor),
                         "grad_explosion": float(grad_explosion_factor),
                         "step_stall": float(stall_factor)}
        # a stall is SUSTAINED by definition: on a shared host a single
        # step 10x above a ~ms median is a scheduler hiccup, and a trip
        # per hiccup would make step_stall unusable off-TPU — require
        # this many consecutive breaching steps (spike/explosion stay
        # single-shot: those are legitimately one-step events)
        self._needed = {"loss_spike": 1, "grad_explosion": 1,
                        "step_stall": max(int(stall_consecutive), 1)}
        self._window_len = int(window)
        self._streak = {}               # (kind, key) -> breach streak
        self._windows = {}              # (kind, key) -> deque
        self._cool = {}
        self._lock = threading.Lock()

    def window(self, kind, key=None):
        """This (kind, key)'s trailing window (created on demand)."""
        with self._lock:
            w = self._windows.get((kind, key))
            if w is None:
                w = self._windows[(kind, key)] = collections.deque(
                    maxlen=self._window_len)
            return w

    def observe(self, step=None, loss=None, grad_norm=None,
                step_ms=None, step_ms_key=None):
        """Judge this step's signals; returns the list of kinds that
        tripped (usually empty). ``step_ms_key`` scopes the stall
        baseline per workload — a loop alternating ~5 ms eval steps
        with ~100 ms train steps must not read its train steps as
        stalls of the eval baseline, so ``Executor.run`` passes its
        compiled-step identity here and each gets its own window."""
        tripped = []
        for kind, signal, value, key in (
                ("loss_spike", "loss", loss, None),
                ("grad_explosion", "grad_global_norm", grad_norm,
                 None),
                ("step_stall", "step_ms", step_ms, step_ms_key)):
            if value is None:
                continue
            value = float(value)
            if value != value or value in (float("inf"),
                                           float("-inf")):
                # a non-finite signal IS the anomaly — never a window
                # sample (one NaN in the deque would poison the median
                # baseline for `window` observations)
                if not self._cooling("non_finite"):
                    self._fire("non_finite",
                               {"signal": signal,
                                "value": repr(value)}, step)
                    tripped.append("non_finite")
            elif self._judge(kind, signal, value, step, key=key):
                tripped.append(kind)
        return tripped

    def _cooling(self, kind):
        """Tick the kind's cooldown by ONE OBSERVATION (the docstring's
        unit — a breach-based tick would swallow the next ``cooldown``
        genuine, well-separated anomalies); True while still cooling."""
        with self._lock:
            c = self._cool.get(kind, 0)
            if c > 0:
                self._cool[kind] = c - 1
                return True
        return False

    def _judge(self, kind, signal, value, step, key=None):
        cooling = self._cooling(kind)
        win = self.window(kind, key)
        wkey = (kind, key)
        with self._lock:
            baseline = statistics.median(win) \
                if len(win) >= self.min_samples else None
            breach = (baseline is not None and baseline > 0
                      and value > self._factors[kind] * baseline)
            # breaching values stay OUT of the window: a sustained
            # stall must not drag the baseline up toward itself while
            # the consecutive-breach count is still accumulating
            if not breach:
                win.append(value)
                self._streak[wkey] = 0
                return False
            self._streak[wkey] = self._streak.get(wkey, 0) + 1
            armed = self._streak[wkey] >= self._needed[kind]
            if armed:
                self._streak[wkey] = 0
        if not armed or cooling:
            return False
        self._fire(kind, {"signal": signal, "value": value,
                          "median": baseline,
                          "factor": self._factors[kind]}, step)
        return True

    def _fire(self, kind, report, step):
        with self._lock:
            self._cool[kind] = self.cooldown
        trip(kind, report=report, step=step)


#: process-wide detector the executor / tensorwatch feed when enabled
DETECTOR = AnomalyDetector()


def enable(**kwargs):
    """Arm the detector (fresh windows; kwargs go to AnomalyDetector)
    and declare this process healthy until proven otherwise."""
    global _enabled, DETECTOR
    DETECTOR = AnomalyDetector(**kwargs)
    _enabled = True
    _g_health.set(1.0)
    return DETECTOR


def disable():
    global _enabled
    _enabled = False


def is_enabled():
    return _enabled


# -- launcher-side readers (stdlib-only, over parsed .prom snapshots) -------
def _rank_step_ms(samples):
    s = samples.get(("executor_step_ms_sum", ()), 0.0)
    c = samples.get(("executor_step_ms_count", ()), 0.0)
    return (s / c) if c else None


def straggler_ranks(snaps, skew=1.75):
    """Ranks whose mean step time exceeds ``skew``× the median rank's.
    ``snaps``: {rank: (types, samples)} from
    exporter.read_rank_snapshots. Needs >= 3 reporting ranks — with 2
    there is no quorum for which one is slow."""
    ms = {}
    for r, (_types, samples) in snaps.items():
        v = _rank_step_ms(samples)
        if v:
            ms[r] = v
    if len(ms) < 3:
        return []
    med = statistics.median(ms.values())
    if med <= 0:
        return []
    return sorted(r for r, v in ms.items() if v > skew * med)


def job_health(snaps, skew=1.75):
    """(health string, straggler rank list) for the launcher's status
    line: ``ok``, or marks like ``anomaly:non_finite`` /
    ``straggler:r3`` joined with ``;``."""
    kinds = set()
    unhealthy = False
    for _r, (_types, samples) in snaps.items():
        for (name, labels), v in samples.items():
            if v <= 0:
                if name == "train_health":
                    unhealthy = True
                continue
            if name == "anomaly_trips_total":
                kinds.update(lv for ln, lv in labels if ln == "kind")
            elif name == "nonfinite_trips_total":
                kinds.add("non_finite")
    marks = []
    if kinds:
        marks.append("anomaly:" + ",".join(sorted(kinds)))
    elif unhealthy:
        marks.append("anomaly")
    stragglers = straggler_ranks(snaps, skew=skew)
    if stragglers:
        marks.append("straggler:"
                     + "+".join(f"r{r}" for r in stragglers))
    return (";".join(marks) if marks else "ok"), stragglers
