"""XLA cost analytics: per-compiled-segment FLOPs/bytes and MFU.

The reference's profiler answers "where did the time go"; this module
answers "how much of the hardware did we use". Sources:

- ``analyze_lowered(lowered)`` reads jax's
  ``lowered.cost_analysis()`` (XLA's HLO cost model — analytical
  FLOPs/bytes, not measured) for each device segment the executor
  compiles; the executor records them here (``record_segment``) both at
  AOT-compile time (``Executor.prepare``) and lazily on a compiled
  step's first real call.
- ``flops_per_step()`` sums the most recently recorded compiled step's
  segments (older compiled steps — other feed signatures, pre-retrace
  shapes — are superseded, not accumulated: summing two compiles of the
  same program would double-count).
- ``estimate_mfu()`` divides achieved FLOP/s (flops_per_step over the
  ``executor_step_ms`` histogram's mean) by ``peak_flops()``.

``peak_flops()`` is ``PADDLE_TPU_PEAK_FLOPS`` when set, else the v5e
bf16 peak (197 TFLOP/s). On a CPU host that denominator is fiction —
the MFU line is for TPU runs; docs/OBSERVABILITY.md spells out the
caveats. jax is only imported inside functions: this module loads under
the stdlib-only launcher.
"""

import os
import threading

from paddle_tpu.monitor.registry import gauge

__all__ = [
    "analyze_lowered", "record_segment", "segments", "flops_per_step",
    "bytes_per_step", "estimate_mfu", "peak_flops", "reset",
]

#: v5e bf16 peak, the chip this repo benches on (bench.py uses the same
#: constant); override with PADDLE_TPU_PEAK_FLOPS for other hardware
DEFAULT_PEAK_FLOPS = 197e12

_lock = threading.Lock()
_segments = {}                  # group -> {index: {"flops","bytes"}}
_latest_group = None

_g_flops = gauge(
    "segment_flops",
    "Analytical FLOPs per execution of each compiled device segment "
    "(XLA cost model via lowered.cost_analysis)", labels=("segment",))
_g_bytes = gauge(
    "segment_bytes",
    "Analytical bytes accessed per execution of each compiled device "
    "segment", labels=("segment",))


def analyze_lowered(lowered):
    """{'flops': float, 'bytes': float} from a ``jax.stages.Lowered``
    (or compiled) object, or None when the backend offers no cost
    model. Handles both the dict and the [dict] return shapes jax has
    used across versions."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    return {"flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes": float(ca.get("bytes accessed", 0.0) or 0.0)}


def record_segment(group, index, analysis):
    """Record one device segment's cost under ``group`` (an identity
    for the compiled step, e.g. ``id(step)``); the latest group becomes
    the per-step total ``flops_per_step`` reports. The gauges mirror
    ONLY the latest group: when a new compiled step starts recording,
    the superseded step's series are dropped — otherwise a retrace from
    2 segments down to 1 would leave a stale ``segment="1"`` series
    inflating every consumer that sums the gauge (the launcher's MFU
    status line does)."""
    global _latest_group
    if not analysis:
        return
    with _lock:
        if group != _latest_group:
            _g_flops.clear()
            _g_bytes.clear()
        _segments.setdefault(group, {})[int(index)] = dict(analysis)
        _latest_group = group
    _g_flops.set(analysis["flops"], segment=str(index))
    _g_bytes.set(analysis["bytes"], segment=str(index))


def segments(group=None):
    """{segment index: {"flops","bytes"}} for ``group`` (default: the
    most recently recorded compiled step)."""
    with _lock:
        g = _latest_group if group is None else group
        return {i: dict(a) for i, a in _segments.get(g, {}).items()}


def _total(key):
    with _lock:
        segs = _segments.get(_latest_group, {})
        return sum(a.get(key, 0.0) for a in segs.values())


def flops_per_step():
    return _total("flops")


def bytes_per_step():
    return _total("bytes")


def peak_flops():
    v = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    try:
        return float(v) if v else DEFAULT_PEAK_FLOPS
    except ValueError:
        return DEFAULT_PEAK_FLOPS


def estimate_mfu(ms_per_step=None):
    """Model FLOPs utilization in [0, 1], or None when either side of
    the ratio is missing. ``ms_per_step`` defaults to the mean of the
    ``executor_step_ms`` histogram (wall time around dispatch — on a
    host-overhead-bound model this UNDERSTATES device utilization;
    see docs/OBSERVABILITY.md)."""
    flops = flops_per_step()
    if not flops:
        return None
    if ms_per_step is None:
        from paddle_tpu.monitor.registry import REGISTRY
        h = REGISTRY.get("executor_step_ms")
        if h is None or h.count() == 0:
            return None
        ms_per_step = h.sum() / h.count()
    if ms_per_step <= 0:
        return None
    return flops / (ms_per_step / 1e3) / peak_flops()


def reset():
    """Forget recorded segments and their gauge series (tests)."""
    global _latest_group
    with _lock:
        _segments.clear()
        _latest_group = None
    _g_flops.clear()
    _g_bytes.clear()
