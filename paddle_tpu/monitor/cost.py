"""XLA cost analytics: per-compiled-segment FLOPs/bytes and MFU.

The reference's profiler answers "where did the time go"; this module
answers "how much of the hardware did we use". Sources:

- ``analyze_lowered(lowered)`` reads jax's
  ``lowered.cost_analysis()`` (XLA's HLO cost model — analytical
  FLOPs/bytes, not measured) for each device segment the executor
  compiles; the executor records them here (``record_segment``) both at
  AOT-compile time (``Executor.prepare``) and lazily on a compiled
  step's first real call.
- ``flops_per_step()`` sums the most recently recorded compiled step's
  segments (older compiled steps — other feed signatures, pre-retrace
  shapes — are superseded, not accumulated: summing two compiles of the
  same program would double-count).
- ``estimate_comm(compiled.as_text())`` estimates cross-device
  collective bytes from the post-SPMD optimized HLO (collectives are
  inserted at COMPILE time, so the pre-partition lowering can't see
  them); the executor records it at AOT-compile time
  (``record_segment_comm`` → ``segment_comm_bytes`` gauge,
  ``comm_bytes_per_step()``), and ``bench.py shard`` reports it per
  mesh topology.
- ``estimate_mfu()`` divides achieved FLOP/s (flops_per_step over the
  ``executor_step_ms`` histogram's mean) by ``peak_flops()``.

``peak_flops()`` is ``PADDLE_TPU_PEAK_FLOPS`` when set, else the v5e
bf16 peak (197 TFLOP/s). On a CPU host that denominator is fiction —
the MFU line is for TPU runs; docs/OBSERVABILITY.md spells out the
caveats. jax is only imported inside functions: this module loads under
the stdlib-only launcher.
"""

import os
import re
import threading

from paddle_tpu.monitor.registry import counter, gauge, histogram

__all__ = [
    "analyze_lowered", "estimate_comm", "record_segment",
    "record_segment_comm", "segments", "flops_per_step",
    "bytes_per_step", "comm_bytes_per_step", "estimate_mfu",
    "peak_flops", "record_pass", "pass_evidence", "reset",
]

#: v5e bf16 peak, the chip this repo benches on (bench.py uses the same
#: constant); override with PADDLE_TPU_PEAK_FLOPS for other hardware
DEFAULT_PEAK_FLOPS = 197e12

_lock = threading.Lock()
_segments = {}                  # group -> {index: {"flops","bytes"}}
_latest_group = None

_g_flops = gauge(
    "segment_flops",
    "Analytical FLOPs per execution of each compiled device segment "
    "(XLA cost model via lowered.cost_analysis)", labels=("segment",))
_g_bytes = gauge(
    "segment_bytes",
    "Analytical bytes accessed per execution of each compiled device "
    "segment", labels=("segment",))
_g_comm = gauge(
    "segment_comm_bytes",
    "Estimated cross-device collective bytes per execution of each "
    "compiled device segment (result-buffer bytes of the collective "
    "ops in the post-SPMD optimized HLO)", labels=("segment",))

# program-level pass pipeline evidence (static/opt_passes.py): one
# record_pass call per pass application at step-compile / export time
_c_pass_runs = counter(
    "program_pass_runs_total",
    "Applications of each program-level optimization pass "
    "(static/opt_passes.py; one per pass per step compile/export)",
    labels=("pass",))
_c_pass_removed = counter(
    "program_pass_ops_removed_total",
    "Program ops removed (folded, fused away, or dead-eliminated) by "
    "each optimization pass, summed over applications",
    labels=("pass",))
_h_pass_ms = histogram(
    "program_pass_ms",
    "Wall ms per optimization-pass application (program-level pass "
    "pipeline ahead of segment compilation)")
_g_pass_flops_delta = gauge(
    "program_pass_flops_delta",
    "Predicted analytical-FLOPs change of the last application of each "
    "optimization pass (post minus pre lowering cost_analysis, "
    "negative = cheaper; FLAGS_pass_cost_evidence probe)",
    labels=("pass",))
_g_pass_bytes_delta = gauge(
    "program_pass_bytes_delta",
    "Predicted bytes-accessed change of the last application of each "
    "optimization pass (post minus pre lowering cost_analysis, "
    "negative = cheaper; FLAGS_pass_cost_evidence probe)",
    labels=("pass",))

_pass_totals = {}               # pass name -> {"runs", "ops_removed"}

# collective instructions in XLA's post-SPMD optimized HLO text; the
# result type precedes the op name ("%x = f32[4,8]{1,0} all-reduce(…"
# or a tuple "(f32[128]{0}, f32[64]{0})" for fused buckets). Async
# split pairs count on -done ONLY: a -start op's result tuple bundles
# operands + results (+ scheduling context), so counting it would
# tally ~2x the result bytes on backends that lower collectives
# asynchronously (TPU) while synchronous lowerings (CPU) count 1x —
# the -done result is exactly the collective result on every backend.
_COLL_RE = re.compile(
    r"=\s+(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)(-start|-done)?\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _type_bytes(type_str):
    total = 0.0
    for dt, dims in _SHAPE_RE.findall(type_str):
        size = _DTYPE_BYTES.get(dt)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def estimate_comm(hlo_text):
    """{'comm_bytes': float, 'collectives': {op: count}} from a
    compiled executable's optimized HLO text (``compiled.as_text()``),
    or None when the text carries no parseable module. The estimate is
    the sum of collective RESULT-buffer bytes per execution — a
    lower-bound proxy for wire traffic (a ring all-reduce moves
    ~2(n-1)/n of it per hop), comparable across topologies AND
    backends because the convention is fixed: async-lowered pairs
    (TPU) count their -done result, never the -start tuple (operands +
    results + context, which would double-count). Collectives are
    inserted by SPMD partitioning at COMPILE time, so this must read
    the compiled text, not the pre-partition lowering."""
    if not hlo_text:
        return None
    comm = 0.0
    counts = {}
    for type_str, op, suffix in _COLL_RE.findall(hlo_text):
        if suffix == "-start":
            continue
        counts[op] = counts.get(op, 0) + 1
        comm += _type_bytes(type_str)
    return {"comm_bytes": comm, "collectives": counts}


def analyze_lowered(lowered):
    """{'flops': float, 'bytes': float} from a ``jax.stages.Lowered``
    (or compiled) object, or None when the backend offers no cost
    model. Handles both the dict and the [dict] return shapes jax has
    used across versions."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    return {"flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes": float(ca.get("bytes accessed", 0.0) or 0.0)}


def record_segment(group, index, analysis):
    """Record one device segment's cost under ``group`` (an identity
    for the compiled step, e.g. ``id(step)``); the latest group becomes
    the per-step total ``flops_per_step`` reports. The gauges mirror
    ONLY the latest group: when a new compiled step starts recording,
    the superseded step's series are dropped — otherwise a retrace from
    2 segments down to 1 would leave a stale ``segment="1"`` series
    inflating every consumer that sums the gauge (the launcher's MFU
    status line does)."""
    global _latest_group
    if not analysis:
        return
    with _lock:
        if group != _latest_group:
            _g_flops.clear()
            _g_bytes.clear()
            _g_comm.clear()
        # merge, don't replace: comm bytes for the same segment may
        # already have been recorded (record_segment_comm)
        _segments.setdefault(group, {}).setdefault(
            int(index), {}).update(analysis)
        _latest_group = group
    _g_flops.set(analysis["flops"], segment=str(index))
    _g_bytes.set(analysis["bytes"], segment=str(index))


def record_segment_comm(group, index, comm):
    """Record one device segment's estimated collective bytes (the
    ``estimate_comm`` result) under ``group`` — the executor calls this
    at AOT-compile time (``Executor.prepare``), when the compiled
    executable's HLO text is in hand; bench modes call it for their own
    jitted steps. Same latest-group gauge semantics as
    ``record_segment``."""
    global _latest_group
    if not comm:
        return
    with _lock:
        if group != _latest_group:
            _g_flops.clear()
            _g_bytes.clear()
            _g_comm.clear()
        entry = _segments.setdefault(group, {}).setdefault(int(index), {})
        entry["comm_bytes"] = float(comm.get("comm_bytes", 0.0))
        entry["collectives"] = dict(comm.get("collectives", {}))
        _latest_group = group
    _g_comm.set(float(comm.get("comm_bytes", 0.0)), segment=str(index))


def segments(group=None):
    """{segment index: {"flops","bytes"}} for ``group`` (default: the
    most recently recorded compiled step)."""
    with _lock:
        g = _latest_group if group is None else group
        return {i: dict(a) for i, a in _segments.get(g, {}).items()}


def _total(key):
    with _lock:
        segs = _segments.get(_latest_group, {})
        return sum(a.get(key, 0.0) for a in segs.values())


def flops_per_step():
    return _total("flops")


def bytes_per_step():
    return _total("bytes")


def comm_bytes_per_step():
    return _total("comm_bytes")


def record_pass(name, ops_removed=0, ms=0.0, flops_delta=None,
                bytes_delta=None):
    """Publish one optimization-pass application (opt_passes drivers
    call this): bumps the program_pass_* metrics and folds into the
    in-process evidence table ``pass_evidence`` reports (the
    ``bench.py passes`` per-pass JSON). ``flops_delta``/``bytes_delta``
    (FLAGS_pass_cost_evidence) are the pass's predicted analytical cost
    change — signed, so they publish as gauges and accumulate in the
    evidence table."""
    name = str(name)
    _c_pass_runs.inc(**{"pass": name})
    if ops_removed:
        _c_pass_removed.inc(float(ops_removed), **{"pass": name})
    _h_pass_ms.observe(float(ms))
    if flops_delta is not None:
        _g_pass_flops_delta.set(float(flops_delta), **{"pass": name})
    if bytes_delta is not None:
        _g_pass_bytes_delta.set(float(bytes_delta), **{"pass": name})
    with _lock:
        t = _pass_totals.setdefault(name,
                                    {"runs": 0, "ops_removed": 0})
        t["runs"] += 1
        t["ops_removed"] += int(ops_removed)
        if flops_delta is not None:
            t["flops_delta"] = t.get("flops_delta", 0.0) \
                + float(flops_delta)
        if bytes_delta is not None:
            t["bytes_delta"] = t.get("bytes_delta", 0.0) \
                + float(bytes_delta)


def pass_evidence():
    """{pass name: {"runs", "ops_removed"[, "flops_delta",
    "bytes_delta"]}} accumulated since process start (or the last
    ``reset``)."""
    with _lock:
        return {k: dict(v) for k, v in _pass_totals.items()}


def peak_flops():
    v = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    try:
        return float(v) if v else DEFAULT_PEAK_FLOPS
    except ValueError:
        return DEFAULT_PEAK_FLOPS


def estimate_mfu(ms_per_step=None):
    """Model FLOPs utilization in [0, 1], or None when either side of
    the ratio is missing. ``ms_per_step`` defaults to the mean of the
    ``executor_step_ms`` histogram (wall time around dispatch — on a
    host-overhead-bound model this UNDERSTATES device utilization;
    see docs/OBSERVABILITY.md)."""
    flops = flops_per_step()
    if not flops:
        return None
    if ms_per_step is None:
        from paddle_tpu.monitor.registry import REGISTRY
        h = REGISTRY.get("executor_step_ms")
        if h is None or h.count() == 0:
            return None
        ms_per_step = h.sum() / h.count()
    if ms_per_step <= 0:
        return None
    return flops / (ms_per_step / 1e3) / peak_flops()


def reset():
    """Forget recorded segments and their gauge series (tests)."""
    global _latest_group
    with _lock:
        _segments.clear()
        _latest_group = None
        _pass_totals.clear()
    _g_flops.clear()
    _g_bytes.clear()
    _g_comm.clear()
