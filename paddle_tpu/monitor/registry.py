"""Process-wide metrics registry: Counter / Gauge / Histogram.

Design constraints, in order:

1. The write path must be cheap enough for ``Executor.run``'s dispatch
   loop and the prefetch worker threads — a contended lock there would
   show up in the very ms/step numbers this module measures. Counters
   and histograms therefore write into THREAD-LOCAL shards (one plain
   dict per thread; dict mutation is atomic under the GIL) and a read
   merges all shards. The only lock is taken once per (metric, thread)
   at shard registration and on reads.
2. Gauges are set rarely (queue depth, per-segment FLOPs), so they use
   a single locked store — last-write-wins is the semantics a gauge
   wants, and merged shards cannot provide it.
3. Stdlib only: the elastic launcher aggregates metrics from worker
   processes whose jax may be wedged; telemetry must not depend on it.

Metric names follow Prometheus conventions (``snake_case``, counters
end in ``_total``, unit suffix like ``_ms`` on histograms). Every name
registered anywhere in the tree must appear in docs/OBSERVABILITY.md's
catalogue — tools/check_metrics.py enforces it as a tier-1 check.
"""

import bisect
import re
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default histogram buckets, in milliseconds — spans the range from a
#: cached-dispatch step (~1 ms on CPU hosts) to a cold XLA compile or a
#: slow checkpoint flush (tens of seconds)
DEFAULT_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


class _ThreadShards:
    """The shard idiom every hot-path recorder here shares (metric
    cells, the profiler's event rings, the flight recorder's span
    stacks): each thread writes its OWN shard — created once and
    registered under the lock, mutated lock-free after — and readers
    take a locked snapshot of the shard list. Dead threads' shards are
    folded (``fold_dead``) or dropped (``None``) on the rare
    registration path, so thread churn cannot grow the list without
    bound."""

    def __init__(self, make_shard, fold_dead=None):
        self._make = make_shard
        self._fold = fold_dead
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._entries = []              # (owner thread, shard)

    def get(self):
        """The calling thread's shard."""
        d = getattr(self._tls, "shard", None)
        if d is None:
            d = self._make()
            self._tls.shard = d
            with self._lock:
                live = []
                for t, sd in self._entries:
                    if t.is_alive():
                        live.append((t, sd))
                    elif self._fold is not None:
                        self._fold(sd)
                live.append((threading.current_thread(), d))
                self._entries = live
        return d

    def shards(self):
        with self._lock:
            return [sd for _t, sd in self._entries]

    def items(self):
        """[(owner thread, shard)] — for readers that need the owner
        (e.g. the flight recorder naming a stuck thread)."""
        with self._lock:
            return list(self._entries)


def _snap_items(d):
    """``list(d.items())`` robust to a concurrent writer inserting a
    new key mid-iteration (each insert is GIL-atomic; the RuntimeError
    is only the resize-during-iteration guard, so retrying converges
    as soon as one pass sees no insert)."""
    while True:
        try:
            return list(d.items())
        except RuntimeError:
            continue


def _fold_cells(acc, shard):
    """Merge a cell shard into an accumulator dict: float cells add,
    list cells (histogram) add elementwise."""
    for k, v in shard.items():
        cur = acc.get(k)
        if cur is None:
            acc[k] = list(v) if isinstance(v, list) else v
        elif isinstance(v, list):
            for i, x in enumerate(v):
                cur[i] += x
        else:
            acc[k] = cur + v


class _Metric:
    """Shared shape: name/help/labelnames + the thread-local shard
    machinery subclasses write through."""

    kind = None

    def __init__(self, name, help="", labelnames=()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._retired = {}          # dead threads' cells, folded in
        self._shards = _ThreadShards(
            dict, lambda sd: _fold_cells(self._retired, sd))

    def _shard(self):
        return self._shards.get()

    def _all_shards(self):
        return [self._retired] + self._shards.shards()

    def _labelkey(self, labels):
        if not self.labelnames and not labels:
            return ()
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)


class Counter(_Metric):
    """Monotonic counter. ``inc`` is the lock-free hot path."""

    kind = "counter"

    def inc(self, amount=1.0, **labels):
        if not amount >= 0:          # also rejects NaN
            raise ValueError(f"{self.name}: counters only go up "
                             f"(inc({amount}))")
        key = self._labelkey(labels)
        shard = self._shard()
        shard[key] = shard.get(key, 0.0) + amount

    def value(self, **labels):
        key = self._labelkey(labels)
        return sum(s.get(key, 0.0) for s in self._all_shards())

    def samples(self):
        """{labelvalues tuple: merged value}."""
        out = {}
        for s in self._all_shards():
            for k, v in _snap_items(s):
                out[k] = out.get(k, 0.0) + v
        return out


class Gauge(_Metric):
    """Point-in-time value; single locked store (last write wins)."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values = {}

    def set(self, value, **labels):
        key = self._labelkey(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount=1.0, **labels):
        key = self._labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount=1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        key = self._labelkey(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def clear(self):
        """Drop every labeled series — for gauges that describe a
        superseded object (e.g. a recompiled step's segments), where a
        stale series would otherwise linger in exports forever."""
        with self._lock:
            self._values.clear()

    def remove(self, **labels):
        """Drop ONE labeled series — for gauges whose label values
        rotate (e.g. the trace exemplar's ``trace_id``): without
        removal every superseded label value would linger in exports
        as unbounded series cardinality."""
        key = self._labelkey(labels)
        with self._lock:
            self._values.pop(key, None)

    def samples(self):
        with self._lock:
            return dict(self._values)


class Histogram(_Metric):
    """Bucketed distribution; ``observe`` is the lock-free hot path.

    Per-shard cell layout: ``[count_b0, ..., count_bN, count_inf,
    sum, count]`` with NON-cumulative bucket counts (merging is
    elementwise add; the exporter cumulates for Prometheus ``le``)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS_MS):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"{self.name}: need at least one bucket")
        self.buckets = bs

    def observe(self, value, **labels):
        key = self._labelkey(labels)
        shard = self._shard()
        cell = shard.get(key)
        if cell is None:
            cell = shard[key] = [0] * (len(self.buckets) + 1) + [0.0, 0]
        cell[bisect.bisect_left(self.buckets, value)] += 1
        cell[-2] += value
        cell[-1] += 1

    def _merged(self):
        out = {}
        nb = len(self.buckets) + 3
        for s in self._all_shards():
            for k, cell in _snap_items(s):
                acc = out.get(k)
                if acc is None:
                    acc = out[k] = [0] * (nb - 2) + [0.0, 0]
                for i in range(nb):
                    acc[i] += cell[i]
        return out

    def samples(self):
        """{labelvalues: (cumulative bucket counts incl +Inf, sum,
        count)} — the exporter's rendering currency."""
        out = {}
        for k, cell in self._merged().items():
            cum, running = [], 0
            for c in cell[:-2]:
                running += c
                cum.append(running)
            out[k] = (cum, cell[-2], cell[-1])
        return out

    def count(self, **labels):
        key = self._labelkey(labels)
        return sum(s.get(key, [0.0, 0])[-1] for s in self._all_shards())

    def sum(self, **labels):
        key = self._labelkey(labels)
        return sum(s.get(key, [0.0, 0])[-2] for s in self._all_shards())


class Registry:
    """Name → metric table with get-or-create semantics: instrumenting
    modules declare their metrics at import with ``counter(...)`` etc.;
    re-declaring an existing name returns the SAME object iff kind and
    labels match (so e.g. launcher and exporter both naming
    ``restarts_total`` agree), and raises otherwise."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                want = kw.get("buckets")
                if want is not None and tuple(sorted(
                        float(b) for b in want)) != m.buckets:
                    # silently handing back other buckets would put
                    # this caller's observations in the wrong ranges
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS_MS):
        # the default-sentinel means "whatever is registered": only an
        # EXPLICIT bucket spec conflicts with an existing one
        if buckets is DEFAULT_BUCKETS_MS:
            return self._get_or_create(Histogram, name, help, labels)
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def collect(self):
        """All metrics, name-sorted (the exporter's iteration order)."""
        with self._lock:
            ms = list(self._metrics.values())
        return sorted(ms, key=lambda m: m.name)

    def clear(self):
        """Drop every metric — TESTS ONLY: instrumented modules hold
        references to their metric objects, which keep counting but
        stop being exported after a clear."""
        with self._lock:
            self._metrics.clear()


#: the process-wide default registry every instrumented layer writes to
REGISTRY = Registry()


def counter(name, help="", labels=(), registry=None):
    return (registry or REGISTRY).counter(name, help, labels)


def gauge(name, help="", labels=(), registry=None):
    return (registry or REGISTRY).gauge(name, help, labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS_MS,
              registry=None):
    return (registry or REGISTRY).histogram(name, help, labels, buckets)
