"""In-graph numerics sentinels + the non-finite localizer.

The reference ships runtime nan/inf checking as a first-class switch:
``FLAGS_check_nan_inf`` makes ``framework/operator.cc`` scan every
op's outputs after every kernel launch. A per-op host-side scan is
exactly what the TPU design cannot afford — the whole block is ONE
fused XLA computation, and a host check per op would both break the
fusion and serialize the dispatch pipeline. The TPU-native shape of
the same switch, implemented here and wired through
``static/executor.py``:

- **Sentinels, fused in-graph**: with ``FLAGS_check_nan_inf`` on, each
  compiled device segment also computes ``sentinel()`` — one fused
  ``isfinite``-reduction over every tensor the segment writes
  (outputs, grads, optimizer state), yielding ONE boolean scalar per
  segment. The reduction rides the same XLA computation (no extra
  dispatch); the only host cost is materializing that scalar once per
  step, which the executor does at the point it would block anyway.
- **Bisecting localizer**: a tripped sentinel says "this segment went
  non-finite", not where. ``localize()`` re-runs the offending step
  EAGERLY per-op from the (un-donated, still-live) pre-step state,
  recording a device-side cumulative finiteness flag after every op —
  still no host syncs — then BISECTS the cumulative flags (monotone:
  once False, stays False) with O(log n_ops) host syncs to the first
  op whose outputs went non-finite, and names the first non-finite
  output tensor with nan/inf counts. For the ``autodiff`` pseudo-op
  the per-gradient leaves are checked individually, so a bad
  ``<param>@GRAD`` is named precisely.
- **Postmortem**: ``handle_trip`` records the trip in the metrics
  registry, routes it through ``monitor.anomaly`` (flight-recorder
  dump with the localizer's report attached, when armed), and raises
  ``NonFiniteError`` carrying the report.

Costs, so the trade is explicit: under the flag the executor skips
buffer donation (the pre-step state must survive for the replay), so
peak memory roughly doubles and each step syncs on one scalar per
segment. ``bench.py numerics`` measures the step-time side of that on
interleaved A/B windows. Everything jax is imported lazily — the
stdlib-only launcher can import ``paddle_tpu.monitor`` freely.

Docs: docs/DEBUGGING.md.
"""

from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.monitor.registry import counter

__all__ = ["NonFiniteError", "sentinel", "localize", "handle_trip",
           "SENTINEL_KEY"]

#: key the checked segment functions return their fused flag under —
#: "@" keeps it out of any legal program var namespace
SENTINEL_KEY = "@sentinel@"

_m_trips = counter(
    "nonfinite_trips_total",
    "In-graph isfinite-sentinel trips (FLAGS_check_nan_inf): steps "
    "whose compiled segment produced a nan/inf tensor")


class NonFiniteError(EnforceNotMet):
    """A step produced nan/inf under FLAGS_check_nan_inf. ``report``
    carries the localizer's findings (first bad tensor/op, counts,
    postmortem path) as a dict — the same dict the postmortem JSON
    embeds under ``anomaly``."""

    def __init__(self, msg, report=None):
        super().__init__(msg)
        self.report = dict(report or {})


def _finite_flag(v):
    """0-d device bool: all elements finite — or None for values the
    check cannot apply to (ints, bools, non-arrays)."""
    import jax.numpy as jnp
    if not hasattr(v, "dtype"):
        return None
    try:
        if not jnp.issubdtype(v.dtype, jnp.inexact):
            return None
        return jnp.all(jnp.isfinite(v))
    except (TypeError, ValueError):
        return None


def sentinel(values):
    """ONE fused scalar: True iff every float element of every value is
    finite. Traced inside the compiled segment, so the reductions fuse
    into the step's own XLA computation."""
    import jax.numpy as jnp
    flags = []
    for v in values:
        f = _finite_flag(v)
        if f is not None:
            flags.append(f)
    if not flags:
        return jnp.asarray(True)
    if len(flags) == 1:
        return flags[0]
    return jnp.all(jnp.stack(flags))


def _replay_records(step, state, feeds, base_key, step_idx, end_seg,
                    want_outputs_of=None):
    """Eagerly re-run segments [0, end_seg] per-op, returning
    ``(records, wanted_outputs)``. ``records`` holds one
    ``(op_idx, op_type, [(name, flag)], cum)`` per executed op, where
    ``flag``/``cum`` are device-side 0-d booleans (cum = AND of all
    flags so far — the monotone signal the bisection needs). No host
    syncs happen here, and records hold only those scalars — NOT the
    output tensors, whose superseded versions (pre-update params,
    every intermediate) would otherwise all stay live at once on a
    model already near its memory limit. ``want_outputs_of=k`` makes
    the replay return op k's output dict and STOP there (the second,
    bounded pass after the bisection has identified the culprit)."""
    import jax
    import jax.numpy as jnp

    env = dict(step.constants)
    env.update(state)
    env.update(feeds)
    records = []
    cum = jnp.asarray(True)
    ops = step.ops
    for (is_host, lo, hi) in step.segs[:end_seg + 1]:
        seg_start_env = dict(env)
        for k in range(lo, hi):
            op = ops[k]
            if op.type == "autodiff":
                pnames = op.attrs["params"]
                loss_name = op.attrs["loss"]
                base = {n: v for n, v in seg_start_env.items()
                        if n not in pnames}

                def fwd(params, _base=base, _lo=lo, _k=k,
                        _loss=loss_name):
                    e = dict(_base)
                    e.update(params)
                    e = step.interpret(e, _lo, _k, base_key, step_idx)
                    return jnp.sum(e[_loss]), e

                params = {n: seg_start_env[n] for n in pnames}
                (_, env2), grads = jax.value_and_grad(
                    fwd, has_aux=True)(params)
                env.update(env2)
                outs = {n + "@GRAD": grads[n] for n in pnames}
                env.update(outs)
            else:
                env = step.interpret(env, k, k + 1, base_key, step_idx)
                outs = {n: env[n] for n in op.output_names()
                        if n in env}
            if want_outputs_of == k:
                return records, outs
            flags = []
            for name, v in sorted(outs.items()):
                f = _finite_flag(v)
                if f is not None:
                    flags.append((name, f))
            if flags:
                cum = jnp.logical_and(
                    cum, jnp.all(jnp.stack([f for _, f in flags])))
            records.append((k, op.type, flags, cum))
    return records, None


def localize(step, state, feeds, base_key, step_idx, bad_dev_index):
    """Name the first non-finite tensor and its producing op by eager
    replay + bisection (module docstring). Returns a report dict, or
    one with ``localized=False`` when replay is unsafe (the program
    has host ops — RPC sends, saves — whose re-execution would repeat
    side effects) or found nothing (the trip did not reproduce)."""
    import numpy as np

    # map the tripped device-segment index to its segment, refusing to
    # replay across host ops
    dev = -1
    end_seg = None
    for si, (is_host, _a, _b) in enumerate(step.segs):
        if is_host:
            return {"localized": False, "segment": int(bad_dev_index),
                    "why": "program contains host ops (RPC/save); "
                           "eager replay would repeat their side "
                           "effects"}
        dev += 1
        if dev == bad_dev_index:
            end_seg = si
            break
    if end_seg is None:
        return {"localized": False, "segment": int(bad_dev_index),
                "why": "tripped segment index out of range"}
    try:
        records, _ = _replay_records(step, state, feeds, base_key,
                                     step_idx, end_seg)
    except Exception as e:      # the replay must never mask the trip
        return {"localized": False, "segment": int(bad_dev_index),
                "why": f"eager replay failed: "
                       f"{type(e).__name__}: {e}"}
    if not records or bool(np.asarray(records[-1][3])):
        return {"localized": False, "segment": int(bad_dev_index),
                "why": "sentinel tripped but the eager replay stayed "
                       "finite (non-deterministic op or stale state?)"}
    # bisect the monotone cumulative flags: O(log n_ops) host syncs
    lo_i, hi_i = 0, len(records) - 1
    while lo_i < hi_i:
        mid = (lo_i + hi_i) // 2
        if bool(np.asarray(records[mid][3])):
            lo_i = mid + 1
        else:
            hi_i = mid
    op_idx, op_type, flags, _ = records[lo_i]
    # second bounded replay: fetch ONLY the culprit op's outputs (the
    # first pass deliberately dropped tensors to keep memory flat)
    try:
        _, outs = _replay_records(step, state, feeds, base_key,
                                  step_idx, end_seg,
                                  want_outputs_of=op_idx)
    except Exception as e:
        return {"localized": False, "segment": int(bad_dev_index),
                "op_index": int(op_idx), "op_type": op_type,
                "why": f"culprit-op re-execution failed: "
                       f"{type(e).__name__}: {e}"}
    outs = outs or {}
    for name, f in flags:
        if bool(np.asarray(f)) or name not in outs:
            continue
        arr = np.asarray(outs[name])
        nan = int(np.isnan(arr).sum())
        inf = int(np.isinf(arr).sum())
        return {
            "localized": True,
            "tensor": name,
            "op_type": op_type,
            "op_index": int(op_idx),
            "segment": int(bad_dev_index),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "nan_count": nan,
            "inf_count": inf,
            "size": int(arr.size),
        }
    return {"localized": False, "segment": int(bad_dev_index),
            "why": "bad op found but no single non-finite output "
                   "(flag/value mismatch)"}


def handle_trip(step, state, feeds, base_key, step_idx, bad_dev_index):
    """The executor's trip path: count it, localize it, leave a
    postmortem (via monitor.anomaly, when the flight recorder is
    armed), raise NonFiniteError. Never returns."""
    from paddle_tpu.monitor import anomaly

    _m_trips.inc()
    report = localize(step, state, feeds, base_key, step_idx,
                      bad_dev_index)
    report["step"] = int(step_idx)
    path = anomaly.trip("non_finite", report=report,
                        step=int(step_idx))
    if path:
        report["postmortem"] = path
    if report.get("localized"):
        where = (f"first non-finite tensor {report['tensor']!r} "
                 f"(shape {tuple(report['shape'])}, "
                 f"{report['nan_count']} nan / {report['inf_count']} "
                 f"inf of {report['size']}) produced by op "
                 f"{report['op_type']!r} at position "
                 f"{report['op_index']}")
    else:
        where = (f"in device segment {report['segment']} "
                 f"(not localized: {report.get('why')})")
    raise NonFiniteError(
        f"FLAGS_check_nan_inf: step {int(step_idx)} produced "
        f"nan/inf — {where}"
        + (f"; postmortem: {path}" if path else ""),
        report=report)
