"""Flight recorder: a bounded ring of recent spans/steps that dumps a
postmortem JSON when the process dies.

The elastic supervisor (distributed/launch.py) made death routine — a
hung rank is killed and restarted, a preempted job is SIGTERMed — but
until now every kill discarded all evidence of what the rank was doing.
This module keeps a small always-on ring of recent events (profiler
spans via ``RecordEvent``, executor steps, anything ``note()``d) plus
the stack of spans currently IN FLIGHT per thread, and writes them — with
a full metrics-registry snapshot — as JSON when:

- an uncaught exception unwinds the process (``sys.excepthook`` chain),
- SIGTERM arrives (the launcher's watchdog kill and pod preemption both
  deliver it; the handler dumps, then chains to any previously
  installed handler so ``auto_checkpoint``'s preemption flush still
  runs),
- the user calls ``dump()`` explicitly.

The launcher exports ``PADDLE_POSTMORTEM_DIR=<log_dir>/postmortem`` to
every worker; ``install_from_env()`` (call it first thing in a worker)
arms the recorder iff that env is present, so production code pays one
boolean check per event when unsupervised. A hung rank's dump names the
span it was stuck inside — the "why did rank 3 die" answer the ROADMAP
asks for. Overhead when armed is one deque append per span.

Dump files are ``<dir>/rank<R>.<pid>.<reason>.json``, written
atomically; format documented in docs/OBSERVABILITY.md.
"""

import collections
import itertools
import json
import os
import signal
import sys
import threading
import time
import traceback

__all__ = [
    "FlightRecorder", "RECORDER", "ENV_DIR",
    "enable", "disable", "is_enabled", "install_from_env",
    "note", "dump",
]

ENV_DIR = "PADDLE_POSTMORTEM_DIR"

#: module-level fast-path switch — instrumented code checks this single
#: boolean before touching the recorder at all
_enabled = False


class FlightRecorder:
    def __init__(self, capacity=4096):
        from paddle_tpu.monitor.registry import _ThreadShards
        self.capacity = int(capacity)
        self._ring = collections.deque(maxlen=self.capacity)
        self._seq = itertools.count()
        # per-thread in-flight span stacks (the shared registry shard
        # idiom; dead threads' stacks are dropped — a dead thread has
        # nothing in flight)
        self._stacks = _ThreadShards(list)
        self._dir = None
        self._installed = False
        self._prev_term = None
        self._prev_hook = None

    # -- recording (hot path) ----------------------------------------------
    def note(self, kind, name, **data):
        """Append one event to the ring. deque.append is GIL-atomic, so
        concurrent writers need no lock."""
        self._ring.append((next(self._seq), time.time(), kind, name,
                           threading.get_ident(), data or None))

    def span_push(self, name):
        """Open an in-flight span; pairs with ``span_pop``. The stack is
        what a postmortem reports as "what was this thread doing"."""
        self._stacks.get().append((name, time.time()))

    def span_pop(self, name, dur_s):
        st = self._stacks.get()
        if st and st[-1][0] == name:
            st.pop()
        self.note("span", name, dur_ms=round(dur_s * 1e3, 3))

    # -- inspection --------------------------------------------------------
    def in_flight(self):
        """[{name, age_s, thread}] for every span currently open,
        innermost last per thread."""
        now = time.time()
        out = []
        for t, st in self._stacks.items():
            for name, t0 in list(st):
                out.append({"name": name, "age_s": round(now - t0, 3),
                            "thread": t.ident})
        return out

    def events(self):
        return [{"seq": s, "time": t, "kind": k, "name": n,
                 "thread": tid, **({"data": d} if d else {})}
                for s, t, k, n, tid, d in list(self._ring)]

    # -- dumping -----------------------------------------------------------
    def _metrics_snapshot(self):
        try:
            from paddle_tpu.monitor.registry import REGISTRY
            out = {}
            for m in REGISTRY.collect():
                if m.kind == "histogram":
                    out[m.name] = {
                        "|".join(k) or "": {"sum": s, "count": c}
                        for k, (_cum, s, c) in m.samples().items()}
                else:
                    out[m.name] = {"|".join(k) or "": v
                                   for k, v in m.samples().items()}
            return out
        except Exception:       # telemetry must not break the dump
            return {}

    def dump(self, path=None, reason="", extra=None):
        """Write the postmortem JSON; returns the path or None when
        there is nowhere to write (no ``path`` and not installed)."""
        if path is None:
            if self._dir is None:
                return None
            rank = os.environ.get("PADDLE_TRAINER_ID", "0")
            tag = "".join(c if c.isalnum() else "-" for c in reason) \
                or "dump"
            path = os.path.join(
                self._dir, f"rank{rank}.{os.getpid()}.{tag}.json")
        doc = {
            "reason": reason,
            "rank": os.environ.get("PADDLE_TRAINER_ID"),
            "restart_count": os.environ.get("PADDLE_RESTART_COUNT"),
            "pid": os.getpid(),
            "time": time.time(),
            "in_flight_spans": self.in_flight(),
            "events": self.events(),
            "metrics": self._metrics_snapshot(),
        }
        try:
            # when tracing is armed, a dump (SIGTERM, watchdog kill,
            # uncaught exception) also carries the dumping thread's
            # in-flight trace tree — lazy import: the recorder must
            # stay importable standalone
            from paddle_tpu.monitor import trace as _trace_mod
            if _trace_mod._enabled:
                tr = _trace_mod.inflight_report()
                if tr is not None:
                    doc["trace"] = tr
        except Exception:       # telemetry must not break the dump
            pass
        if extra:
            doc.update(extra)
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        return path

    # -- arming ------------------------------------------------------------
    def install(self, dirname):
        """Arm the recorder: dumps go under ``dirname``; SIGTERM and
        uncaught exceptions trigger one. Both hooks CHAIN to whatever
        was installed before (and by running first, a dump happens even
        if a later-installed handler exits the process). Returns an
        undo callable; idempotent."""
        os.makedirs(dirname, exist_ok=True)
        self._dir = dirname
        if self._installed:
            return lambda: None
        self._installed = True

        self._prev_hook = sys.excepthook

        def hook(etype, value, tb):
            self.dump(reason="exception", extra={
                "exception": "".join(traceback.format_exception_only(
                    etype, value)).strip(),
                "traceback": traceback.format_tb(tb)[-10:],
            })
            (self._prev_hook or sys.__excepthook__)(etype, value, tb)

        sys.excepthook = hook

        undo_sig = lambda: None
        if threading.current_thread() is threading.main_thread():
            self._prev_term = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                self.dump(reason="sigterm")
                prev = self._prev_term
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    # preserve default die-by-SIGTERM semantics (the
                    # launcher reads the exit status)
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(signal.SIGTERM, on_term)

            def undo_sig():
                signal.signal(signal.SIGTERM,
                              self._prev_term or signal.SIG_DFL)
                self._prev_term = None

        def undo():
            sys.excepthook = self._prev_hook or sys.__excepthook__
            undo_sig()
            self._installed = False

        return undo


#: process-wide default recorder (what RecordEvent/Executor feed)
RECORDER = FlightRecorder()


def enable(dirname=None):
    """Turn recording on; with ``dirname`` also arm the crash/SIGTERM
    dump hooks there."""
    global _enabled
    _enabled = True
    if dirname:
        RECORDER.install(dirname)
    return RECORDER


def disable():
    global _enabled
    _enabled = False


def is_enabled():
    return _enabled


def install_from_env(env=None):
    """Worker-side hookup: arm the recorder iff the launcher exported
    PADDLE_POSTMORTEM_DIR. Returns the recorder or None."""
    env = os.environ if env is None else env
    d = env.get(ENV_DIR)
    if not d:
        return None
    return enable(d)


def note(kind, name, **data):
    """Module-level convenience: record iff enabled."""
    if _enabled:
        RECORDER.note(kind, name, **data)


def dump(path=None, reason="manual"):
    return RECORDER.dump(path=path, reason=reason)
