"""Opt-in tensor/grad watch: grad global-norm, param-norm,
update-ratio, and AMP loss-scale events in the metrics registry.

The reference debugs training health by printing tensors from inside
the per-op loop; on TPU the step is one fused XLA program, so the
watch statistics are computed IN-GRAPH and ride the step's existing
fetch, costing no device round-trip of their own:

- With ``tensorwatch.enable()`` active at ``Optimizer.minimize()``
  time, the optimizer brackets its update ops with two watch ops:
  ``tensor_watch_pre`` (before clipping: pre-clip grad global norm +
  param global norm — the SAME ``clip.global_norm`` subgraph
  ``GradientClipByGlobalNorm`` builds, so XLA CSE folds the two into
  one reduction) and ``tensor_watch_post`` (after the updates:
  ``‖new − old‖ / ‖old‖`` — the update ratio, the "is my LR sane"
  number). The old params are threaded through as pass-through
  outputs, which keeps them alive across the update inside the XLA
  program: the watch costs one extra param-sized liveness range while
  enabled, nothing when off.
- The stats land in one tiny ``@watch@stats`` vector the executor
  fetches alongside the user's fetch list and publishes here
  (``on_step``) as gauges/histograms. In async mode
  (``return_numpy=False``) publication is one step delayed so the
  watch never adds a sync.
- AMP: ``record_loss_scale`` turns the materialized loss-scale state
  into a ``loss_scale`` gauge and a ``loss_scale_decrements_total``
  counter (each decrement is an overflow event — the fp16 canary);
  ``amp.OptimizerWithMixedPrecision.monitor_state`` is the hookup.

Grad norms also feed ``monitor.anomaly``'s grad-explosion window when
the detector is enabled. jax/numpy are imported lazily: the
stdlib-only launcher can import ``paddle_tpu.monitor`` freely.

Docs: docs/DEBUGGING.md; metric catalogue: docs/OBSERVABILITY.md.
"""

import threading

from paddle_tpu.monitor import flight_recorder as _flight
from paddle_tpu.monitor.registry import counter, gauge, histogram

__all__ = [
    "TensorMonitor", "enable", "disable", "is_enabled", "on_step",
    "flush", "record_loss_scale", "STATS_VAR", "PRE_VAR",
]

#: program var the watch ops write / the executor auto-fetches
STATS_VAR = "@watch@stats"
PRE_VAR = "@watch@prenorms"

_g_grad = gauge(
    "grad_global_norm",
    "Last published step's PRE-CLIP global gradient norm (tensor "
    "watch; the norm GradientClipByGlobalNorm computes)")
_h_grad = histogram(
    "grad_global_norm_per_step",
    "Distribution of the pre-clip global gradient norm across "
    "published steps",
    buckets=(1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 100.0, 1e3, 1e4))
_g_param = gauge(
    "param_global_norm",
    "Last published step's global parameter norm (pre-update)")
_g_ratio = gauge(
    "update_ratio",
    "Last published step's ||new_params - old_params|| / "
    "||old_params|| (tensor watch)")
_g_scale = gauge(
    "loss_scale",
    "Current AMP dynamic loss scale (record_loss_scale)")
_c_scale_dec = counter(
    "loss_scale_decrements_total",
    "AMP loss-scale decrements observed — each one is a non-finite "
    "fp16 gradient event the scaler absorbed")

_enabled = False
_lock = threading.Lock()
_pending = None               # (stats vector, step) awaiting publish
_last_scale = None


def enable():
    """Arm the watch. Programs built (``minimize()``d) while enabled
    carry the watch ops; publication is also gated on this flag. Also
    forgets the loss-scale baseline: a new run starting from its init
    scale must not read as a decrement of the previous run's grown
    scale."""
    global _enabled, _last_scale
    _enabled = True
    _last_scale = None


def disable():
    global _enabled, _last_scale
    _enabled = False
    _last_scale = None
    flush()


def is_enabled():
    return _enabled


# -- in-graph op computes (registered by optimizer.py, which owns the
# -- program layout; traced inside the executor's fused step) --------------
def _watch_pre_compute(ins, attrs):
    import jax.numpy as jnp

    from paddle_tpu import clip as clip_mod
    grads = list(ins.get("Grads", []))
    params = list(ins.get("Params", []))
    gn = clip_mod.global_norm(grads)
    pn = clip_mod.global_norm(params)
    # params pass through: keeps the pre-update values alive for the
    # post op's update-ratio without a second device_put or fetch
    return {"Norms": [jnp.stack([gn, pn])], "PreParams": params}


def _watch_post_compute(ins, attrs):
    import jax.numpy as jnp

    from paddle_tpu import clip as clip_mod
    new = list(ins.get("Params", []))
    old = list(ins.get("PreParams", []))
    pre = ins["PreNorms"][0]
    un = clip_mod.global_norm([n - o for n, o in zip(new, old)])
    ratio = un / jnp.maximum(pre[1], 1e-12)
    return {"Out": [jnp.stack([pre[0], pre[1], un, ratio])]}


# -- host-side publication --------------------------------------------------
def _publish(vec, step=None):
    import numpy as np
    v = np.asarray(vec, dtype=np.float64).ravel()
    if v.size < 4:
        return
    gn, pn, un, ratio = (float(x) for x in v[:4])
    _g_grad.set(gn)
    _h_grad.observe(gn)
    _g_param.set(pn)
    _g_ratio.set(ratio)
    if _flight._enabled:
        _flight.RECORDER.note("watch", "tensorwatch", step=step,
                              grad_norm=round(gn, 6),
                              update_ratio=round(ratio, 8))
    from paddle_tpu.monitor import anomaly
    if anomaly._enabled:
        anomaly.DETECTOR.observe(step=step, grad_norm=gn)


def on_step(stats, step=None, sync=True):
    """The executor's hookup: hand over one step's ``@watch@stats``
    vector. ``sync=True`` publishes immediately (the caller is about
    to block on fetches anyway); ``sync=False`` (async dispatch)
    defers to the NEXT call — by then the device has long finished the
    value, so materializing it cannot stall the pipeline."""
    global _pending
    with _lock:
        prev, _pending = _pending, (None if sync else (stats, step))
    if prev is not None:
        _publish(prev[0], prev[1])
    if sync:
        _publish(stats, step)


def flush():
    """Publish any deferred async-mode stats (end of a training run)."""
    global _pending
    with _lock:
        prev, _pending = _pending, None
    if prev is not None:
        _publish(prev[0], prev[1])


def record_loss_scale(scale, step=None):
    """Publish the AMP dynamic loss scale; count decrements (each is an
    absorbed non-finite-gradient event). Call with the MATERIALIZED
    scale between steps — amp.OptimizerWithMixedPrecision
    .monitor_state does."""
    global _last_scale
    s = float(scale)
    _g_scale.set(s)
    if _last_scale is not None and s < _last_scale:
        _c_scale_dec.inc()
        if _flight._enabled:
            _flight.RECORDER.note("watch", "loss_scale_decrement",
                                  step=step, scale=s)
    _last_scale = s
    return s


class TensorMonitor:
    """Eager/functional-path watch: compute the same stats from
    (params, grads[, new_params]) pytrees and publish them. This DOES
    cost extra device work (the static path's watch ops ride the fused
    step instead) — it is the convenience wrapper for eager loops that
    already materialize their state."""

    def observe(self, params, grads, new_params=None, step=None):
        import jax
        import jax.numpy as jnp

        from paddle_tpu import clip as clip_mod
        gn = clip_mod.global_norm(grads)
        pn = clip_mod.global_norm(params)
        if new_params is not None:
            deltas = jax.tree.map(jnp.subtract, new_params, params)
            un = clip_mod.global_norm(deltas)
            ratio = un / jnp.maximum(pn, 1e-12)
        else:
            un = jnp.zeros(())
            ratio = jnp.zeros(())
        _publish(jnp.stack([gn, pn, un, ratio]), step)
        return float(gn)
