"""Goodput ledger: end-to-end wall-clock attribution across incarnations.

The observability spine (metrics, tracing, the memory ledger) explains
any single step or request; this module answers the production question
those can't: *of the N hours this job ran, how many produced training
progress, and where did the rest go?* Every wall-clock second of a
supervised job is attributed, per rank and per incarnation, to one
phase of an exhaustive vocabulary (``PHASES``), published as monotonic
``goodput_seconds_total{phase}`` counters that the launcher aggregates
into a job-level ``goodput_fraction`` gauge (the ``goodput=`` field of
the status line) and ``tools/goodput_report.py`` merges into a
per-incarnation waterfall.

Phase vocabulary (the ledger is exhaustive by construction — in-run
time splits into compile vs compute, between-run time splits into the
instrumented stalls vs ``device_idle`` residual):

- ``device_compute`` — dispatch + fetch of a compiled step (the only
  phase that counts toward goodput).
- ``compile`` — prepare + dispatch wall time of runs in which a device
  segment (re)traced (``Executor.trace_count`` moved): XLA tracing,
  compilation, or a compile-cache replay.
- ``replay`` — re-execution of steps a crash already paid for: step
  compute at ``step <= replayed-until`` (the previous incarnation's
  last observed step, from the launcher's incarnation records) is lost
  work, not progress.
- ``input_wait`` — the consumer side of ``background_prefetch``
  blocked on an empty queue (producer-bound input pipeline).
- ``device_idle`` — between-run residual no instrumented stall claims:
  eager host work, logging, the loop body itself.
- ``checkpoint_save`` / ``checkpoint_restore`` — the synchronous parts
  of checkpointing: d2h snapshot + enqueue (or the full durable write
  when sync), ``wait()`` barriers, restore + data-state restore.
- ``collective_wait`` — blocked in PS barriers / reconnect backoff.
- ``startup`` — process spawn (``PADDLE_SPAWN_WALLTIME``, stamped by
  the launcher) to ledger arming: imports, jax init, program build.
- ``restart_downtime`` — launcher-side: gang death to next spawn,
  weighted by the NEW incarnation's world size so launcher seconds and
  rank-seconds add up in one denominator.

The hot path is a single ``_armed`` check when disabled (the bench's
ABBA A/B toggles exactly that), and when armed costs two
``perf_counter`` stamps plus one thread-local counter bump per step.
Stdlib-only: the launcher imports this freely.
"""

import json
import os
import threading
import time

from paddle_tpu.monitor.registry import counter, gauge

__all__ = [
    "PHASES", "enable", "disable", "install_from_env", "attribute",
    "on_run_start", "on_run_end", "on_step", "on_restore",
    "flush_idle", "fraction_of", "phase_seconds_of",
    "record_incarnation", "read_incarnations", "INCARNATIONS_FILE",
    "ENV_DIR", "ENV_SPAWN",
]

#: the exhaustive phase vocabulary; tools/check_metrics.py lints that
#: every ``phase="..."`` literal in the tree is documented in the
#: goodput_seconds_total catalogue row
PHASES = (
    "device_compute", "compile", "replay", "input_wait", "device_idle",
    "checkpoint_save", "checkpoint_restore", "collective_wait",
    "startup", "restart_downtime",
)

ENV_DIR = "PADDLE_GOODPUT_DIR"
ENV_SPAWN = "PADDLE_SPAWN_WALLTIME"
INCARNATIONS_FILE = "incarnations.jsonl"

_c_phase = counter(
    "goodput_seconds_total",
    "Wall-clock seconds attributed to each goodput-ledger phase "
    "(exhaustive vocabulary, see monitor/goodput.py; launcher-side "
    "restart_downtime seconds are multiplied by the new incarnation's "
    "world size so they sum with per-rank seconds)",
    labels=("phase",))
_g_wall = gauge(
    "goodput_wall_seconds",
    "Wall-clock seconds from this process's spawn (or ledger arming) "
    "to its most recent attribution — the per-rank denominator the "
    "phase seconds must sum to (goodput_report asserts within 2%)")
_g_fraction = gauge(
    "goodput_fraction",
    "Job-level goodput: device_compute seconds / all attributed "
    "seconds across ranks + launcher, in [0, 1] (the status line's "
    "goodput= field; set launcher-side only)")
_g_step = gauge(
    "goodput_step",
    "Most recent global training-loop step this rank entered "
    "(auto_checkpoint); the launcher records the max across ranks as "
    "the incarnation's last_step — the replay watermark")
_g_restored = gauge(
    "goodput_restored_step",
    "Checkpoint step this incarnation restored from (unset when it "
    "started fresh); replayed lost work spans "
    "(goodput_restored_step, last_step of the crashed incarnation]")
_c_replayed = counter(
    "goodput_replayed_steps_total",
    "Training-loop steps re-executed below the previous incarnation's "
    "last observed step — work a crash already paid for once")

_armed = False
_lock = threading.Lock()
_origin = None          # wall epoch the wall gauge measures from
_mark = None            # perf_counter of the last attribution boundary
_accounted = 0.0        # externally attributed seconds since _mark
_replay_until = -1      # steps <= this are replayed lost work
_step = None            # current training-loop step (on_step)


def _touch_wall():
    if _origin is not None:
        _g_wall.set(time.time() - _origin)


def _inc(seconds, phase):
    """Unconditional phase credit (callers hold no lock)."""
    if seconds > 0:
        _c_phase.inc(float(seconds), phase=phase)
        _touch_wall()


def enable():
    """Arm the ledger (idempotent). The launcher calls this for its
    own registry; workers arm via ``install_from_env``."""
    global _armed, _origin, _mark
    with _lock:
        if _armed:
            return
        _armed = True
        if _origin is None:
            _origin = time.time()
        _mark = time.perf_counter()


def disable():
    """Disarm: zero recording from here on (the bench A/B's off arm).
    Counters keep their values — the ledger is monotonic."""
    global _armed
    with _lock:
        _armed = False


def install_from_env():
    """Arm under a supervisor: PADDLE_GOODPUT_DIR (exported by
    launch.py next to the heartbeat/postmortem dirs) selects the
    incarnation-record directory; PADDLE_SPAWN_WALLTIME (stamped at
    spawn) prices the ``startup`` phase; the previous incarnation's
    record sets the replay watermark. Returns True when armed."""
    global _replay_until
    d = os.environ.get(ENV_DIR)
    if not d:
        return False
    global _origin
    spawn = os.environ.get(ENV_SPAWN)
    if spawn:
        try:
            _origin = float(spawn)
        except ValueError:
            pass
    enable()
    if _origin is not None:
        _inc(max(0.0, time.time() - _origin), phase="startup")
    recs = read_incarnations(d)
    if recs:
        last = recs[-1].get("last_step")
        if isinstance(last, (int, float)) and last >= 0:
            _replay_until = int(last)
    return True


def attribute(seconds, phase):
    """Credit ``seconds`` to ``phase`` from an instrumented stall seam
    (prefetch wait, checkpoint save/restore, collective wait, restart
    downtime). Also marks them *accounted*, so the between-run residual
    (``device_idle``) and the in-run compute split never double-count
    them. No-op while disarmed — call sites gate on ``_armed`` first
    so the disabled hot path pays one attribute read."""
    global _accounted
    if not _armed or seconds <= 0:
        return
    _inc(seconds, phase=phase)
    with _lock:
        _accounted += seconds


def on_run_start(t_run):
    """Executor.run entry: flush the between-run gap — whatever the
    instrumented stalls didn't claim since the last boundary was the
    host thinking while the device sat idle."""
    global _mark, _accounted
    if not _armed:
        return
    with _lock:
        if _mark is None:
            _mark = t_run
        residual = max(0.0, (t_run - _mark) - _accounted)
        _mark = t_run
        _accounted = 0.0
    _inc(residual, phase="device_idle")


def on_run_end(t_run, t_prep, t_disp, t_disp_end, traced):
    """Executor.run exit: split the in-run window. When a device
    segment (re)traced this run, prepare + dispatch carried the
    trace/compile (first step, signature churn, cache replay); the
    rest — minus any stall seconds attributed mid-run — is device
    compute, or ``replay`` while re-executing steps the previous
    incarnation already reached."""
    global _mark, _accounted
    if not _armed:
        return
    now = time.perf_counter()
    compile_s = ((t_prep - t_run) + (t_disp_end - t_disp)) \
        if traced else 0.0
    with _lock:
        ext = _accounted
        _accounted = 0.0
        _mark = now
    compute_s = max(0.0, (now - t_run) - compile_s - ext)
    if compile_s > 0:
        _inc(compile_s, phase="compile")
    if _step is not None and _step <= _replay_until:
        _inc(compute_s, phase="replay")
    else:
        _inc(compute_s, phase="device_compute")


def on_step(step):
    """Training-loop step marker (auto_checkpoint calls it before the
    step body): publishes the replay watermark source and counts
    replayed steps."""
    global _step
    if not _armed:
        return
    _step = int(step)
    _g_step.set(float(_step))
    if _step <= _replay_until:
        _c_replayed.inc()


def on_restore(step):
    """A checkpoint restore landed on ``step`` (before the +1 resume
    bump)."""
    if not _armed:
        return
    _g_restored.set(float(int(step)))


def flush_idle():
    """Attribute the tail since the last boundary (loop exit to final
    checkpoint/exporter shutdown) so the per-rank phase sum tracks the
    wall gauge to the end."""
    global _mark, _accounted
    if not _armed:
        return
    now = time.perf_counter()
    with _lock:
        if _mark is None:
            _mark = now
        residual = max(0.0, (now - _mark) - _accounted)
        _mark = now
        _accounted = 0.0
    _inc(residual, phase="device_idle")


# -- aggregation helpers (exporter / report side) ---------------------------
def phase_seconds_of(samples):
    """{phase: seconds} out of parsed/aggregated exporter samples
    (``{(name, label_pairs): value}``)."""
    out = {}
    for (name, pairs), v in samples.items():
        if name != "goodput_seconds_total":
            continue
        phase = dict(pairs).get("phase", "?")
        out[phase] = out.get(phase, 0.0) + float(v)
    return out


def fraction_of(samples):
    """device_compute share of all attributed seconds, or None when
    the samples carry no ledger yet."""
    phases = phase_seconds_of(samples)
    total = sum(phases.values())
    if total <= 0:
        return None
    return phases.get("device_compute", 0.0) / total


# -- incarnation records (launcher-side jsonl) ------------------------------
def record_incarnation(dirname, record):
    """Append one gang-incarnation record to
    ``<dirname>/incarnations.jsonl`` (the launcher writes one at every
    gang end — ok, fail, hung, timeout, preempted). One json object
    per line; a torn tail line is skipped by ``read_incarnations``."""
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, INCARNATIONS_FILE)
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def read_incarnations(dirname):
    """Parsed records, file order (incarnation order); unreadable or
    torn lines are skipped."""
    path = os.path.join(dirname, INCARNATIONS_FILE)
    out = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return out
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out
