"""Unified runtime telemetry (the role of the reference's two-layer
host+device profiler plus the monitoring glue it never had).

Four pieces, one API:

- ``monitor.registry`` — process-wide Counter/Gauge/Histogram with
  labels; the write path is lock-free (thread-local shards merged on
  read) so hot loops (``Executor.run``, prefetch workers) can record
  per-step without contention.
- ``monitor.exporter`` — Prometheus text-format snapshots written
  atomically next to each rank's heartbeat file, an optional stdlib
  ``http.server`` ``/metrics`` endpoint, and the launcher-side
  aggregation of per-rank snapshots into a job-level view + one-line
  status log.
- ``monitor.flight_recorder`` — a bounded ring of recent spans/steps
  that dumps a postmortem JSON on crash or SIGTERM (the elastic
  launcher's watchdog kill included), so a hang finally leaves
  evidence.
- ``monitor.cost`` — per-compiled-segment FLOPs/bytes from XLA's cost
  analysis, combined with the step-time histogram into an MFU estimate
  (surfaced by ``profiler.summary()``).
- ``monitor.trace`` — end-to-end distributed tracing: per-request /
  per-step span trees with explicit context propagation across thread
  boundaries, tail sampling, SLO exemplars, per-rank trace files and
  the launcher-side cross-rank merge into one Perfetto timeline.

Training-health observability (the "has the run gone wrong" half,
docs/DEBUGGING.md):

- ``monitor.numerics`` — in-graph isfinite sentinels fused into the
  executor's compiled segments under ``FLAGS_check_nan_inf``, plus the
  bisecting localizer that names the first non-finite tensor and op.
- ``monitor.tensorwatch`` — opt-in grad/param-norm, update-ratio and
  AMP loss-scale watch riding the step's existing fetch.
- ``monitor.anomaly`` — windowed anomaly detector (loss spike, grad
  explosion, step stall, non-finite) that dumps the flight recorder
  with the anomaly named, and the launcher-side straggler/health
  readout over the per-rank snapshots.
- ``monitor.goodput`` — the goodput ledger: every wall-clock second of
  a supervised job attributed to a phase (device compute, compile,
  input wait, checkpoint stall, replayed lost work, restart downtime…)
  per rank and per incarnation; the launcher rolls the per-rank
  counters into a job-level ``goodput_fraction`` and
  ``tools/goodput_report.py`` renders the per-incarnation waterfall
  (docs/DEBUGGING.md "Where did my wall-clock go?").
- ``monitor.memory`` — device-memory observability: compile-time
  per-segment memory ledger from ``compiled.memory_analysis()``, the
  named-entity residency ledger, the sampled HBM poller
  (in-use/limit/utilization/high-water gauges), and typed
  ``OutOfDeviceMemoryError`` postmortems for RESOURCE_EXHAUSTED
  (docs/DEBUGGING.md "Why did the job OOM?").

Everything importable here is stdlib-only at module level (jax/numpy
are touched lazily inside ``cost``/``numerics``/``tensorwatch``): the
elastic launcher — which must supervise workers whose jax is wedged —
can use the exporter, recorder and anomaly readers freely.

Metrics catalogue: docs/OBSERVABILITY.md (kept in sync by
tools/check_metrics.py, a tier-1 CI check).
"""

from paddle_tpu.monitor import anomaly
from paddle_tpu.monitor import cost
from paddle_tpu.monitor import exporter
from paddle_tpu.monitor import flight_recorder
from paddle_tpu.monitor import goodput
from paddle_tpu.monitor import httpd
from paddle_tpu.monitor import memory
from paddle_tpu.monitor import numerics
from paddle_tpu.monitor import registry
from paddle_tpu.monitor import tensorwatch
from paddle_tpu.monitor import trace
from paddle_tpu.monitor.anomaly import AnomalyDetector
from paddle_tpu.monitor.exporter import (
    MetricsServer, RankExporter, render_text, write_snapshot,
)
from paddle_tpu.monitor.flight_recorder import RECORDER, FlightRecorder
from paddle_tpu.monitor.httpd import ThreadedHTTPServerBase
from paddle_tpu.monitor.memory import OutOfDeviceMemoryError
from paddle_tpu.monitor.numerics import NonFiniteError
from paddle_tpu.monitor.registry import (
    REGISTRY, Counter, Gauge, Histogram, Registry, counter, gauge,
    histogram,
)
from paddle_tpu.monitor.tensorwatch import TensorMonitor
from paddle_tpu.monitor.trace import (
    TRACER, TraceContext, Tracer, merge_rank_traces,
)

__all__ = [
    "registry", "exporter", "flight_recorder", "cost", "numerics",
    "tensorwatch", "anomaly", "trace", "memory", "goodput", "httpd",
    "ThreadedHTTPServerBase",
    "Tracer", "TraceContext", "TRACER", "merge_rank_traces",
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram",
    "RankExporter", "MetricsServer", "render_text", "write_snapshot",
    "FlightRecorder", "RECORDER",
    "NonFiniteError", "TensorMonitor", "AnomalyDetector",
    "OutOfDeviceMemoryError",
]
