"""Unified runtime telemetry (the role of the reference's two-layer
host+device profiler plus the monitoring glue it never had).

Four pieces, one API:

- ``monitor.registry`` — process-wide Counter/Gauge/Histogram with
  labels; the write path is lock-free (thread-local shards merged on
  read) so hot loops (``Executor.run``, prefetch workers) can record
  per-step without contention.
- ``monitor.exporter`` — Prometheus text-format snapshots written
  atomically next to each rank's heartbeat file, an optional stdlib
  ``http.server`` ``/metrics`` endpoint, and the launcher-side
  aggregation of per-rank snapshots into a job-level view + one-line
  status log.
- ``monitor.flight_recorder`` — a bounded ring of recent spans/steps
  that dumps a postmortem JSON on crash or SIGTERM (the elastic
  launcher's watchdog kill included), so a hang finally leaves
  evidence.
- ``monitor.cost`` — per-compiled-segment FLOPs/bytes from XLA's cost
  analysis, combined with the step-time histogram into an MFU estimate
  (surfaced by ``profiler.summary()``).

Everything importable here is stdlib-only at module level (jax is
touched lazily inside ``cost``): the elastic launcher — which must
supervise workers whose jax is wedged — can use the exporter and
recorder freely.

Metrics catalogue: docs/OBSERVABILITY.md (kept in sync by
tools/check_metrics.py, a tier-1 CI check).
"""

from paddle_tpu.monitor import cost
from paddle_tpu.monitor import exporter
from paddle_tpu.monitor import flight_recorder
from paddle_tpu.monitor import registry
from paddle_tpu.monitor.exporter import (
    MetricsServer, RankExporter, render_text, write_snapshot,
)
from paddle_tpu.monitor.flight_recorder import RECORDER, FlightRecorder
from paddle_tpu.monitor.registry import (
    REGISTRY, Counter, Gauge, Histogram, Registry, counter, gauge,
    histogram,
)

__all__ = [
    "registry", "exporter", "flight_recorder", "cost",
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram",
    "RankExporter", "MetricsServer", "render_text", "write_snapshot",
    "FlightRecorder", "RECORDER",
]
