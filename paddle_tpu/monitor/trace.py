"""End-to-end distributed tracing: per-request / per-step span trees.

The monitor stack could already say *that* p99 went to 45 ms (serving
SLO histograms) and *that* a step stalled (anomaly detector) — this
module answers *where the time went* for any individual request or
step. It is the profiler/timeline layer of the blueprint's two-layer
design (PAPER.md), rebuilt around EXPLICIT trace-context propagation:

- a **trace** is one causal unit of work — a serving request
  (``submit -> queue-wait -> batch-form -> dispatch-wait -> execute ->
  deliver``) or a training step (``prepare -> feed_stage -> dispatch
  -> fetch``) — identified by a process-unique ``trace_id``;
- a **span** is one timed phase inside it, carrying ``span``/
  ``parent`` ids so the tree survives thread hops: the objects that
  already flow through the system (serving ``_Request``/``MicroBatch``,
  the executor's step, prefetch queue items) carry their
  :class:`TraceContext`, and whatever thread finishes a phase records
  the span against that context — no thread-local guessing across the
  batcher/replica/prefetch-worker boundaries.

Spans are recorded RETROACTIVELY (``record_span(ctx, name, t0, t1)``)
from timestamps the hot paths already take, so the instrumented code
never holds a span open across an await point or thread hand-off.

**Tail sampling** keeps the hot path unmeasurably cheap while keeping
every trace worth keeping: at trace end the whole tree is either
flushed or dropped — errors always kept, SLO-exemplar traces always
kept, the slowest ``slow_keep`` per rolling window always kept, and
the rest kept at ``sample_rate`` (deterministic every-Nth, no RNG on
the hot path). Kept spans land in a bounded ring (the flight-recorder
idiom — in-process inspection via ``spans()``) and, when armed with a
directory, in ``<dir>/rank<N>.trace.jsonl``.

**Exemplars** close the metrics->traces loop: ``record_exemplar``
remembers the trace_id of the slowest observation per window for the
SLO histograms (``serving_request_latency_ms``, ``executor_step_ms``)
and exports it as the ``slo_exemplar_ms{metric,trace_id}`` gauge — so
"p99 spiked" dereferences to one concrete span tree, and the exemplar
trace itself is force-kept.

**Cross-rank merge**: each rank's jsonl opens with a clock-anchor meta
line ``{"t":"meta","epoch":wall,"perf":perf_counter}``; span
timestamps are raw ``perf_counter`` (monotonic — each process's origin
is arbitrary), and :func:`merge_rank_traces` maps every rank onto the
shared epoch timeline via its anchor, emitting ONE Perfetto/Chrome
trace JSON per job (one pid per rank). The launcher runs the merge at
job end when ``--log_dir`` is set.

Everything here is stdlib-only at module level (the launcher-side
merge must work while workers' jax is wedged). The launcher exports
``PADDLE_TRACE_DIR=<log_dir>/traces``; ``install_from_env()`` (wired
into ``auto_checkpoint`` like the flight recorder) arms tracing iff
that env is present. Knobs: ``PADDLE_TRACE_SAMPLE`` (keep rate for
unremarkable traces, default 0.05), ``PADDLE_TRACE_SLOW_KEEP``
(slowest-N reservoir size, default 8). Docs:
docs/OBSERVABILITY.md "Distributed tracing",
docs/DEBUGGING.md "why did p99 spike".
"""

import collections
import itertools
import json
import os
import re
import threading
import time

from paddle_tpu.monitor.registry import counter, gauge

__all__ = [
    "TraceContext", "Tracer", "TRACER", "ENV_DIR",
    "enable", "disable", "is_enabled", "install_from_env",
    "start_trace", "end_trace", "record_span", "record_exemplar",
    "tail_candidate", "stage_note", "adopt_stage", "inflight_report",
    "spans", "flush",
    "merge_rank_traces", "EXEMPLAR_METRICS", "RANK_TRACE_RE",
]

ENV_DIR = "PADDLE_TRACE_DIR"
ENV_SAMPLE = "PADDLE_TRACE_SAMPLE"
ENV_SLOW_KEEP = "PADDLE_TRACE_SLOW_KEEP"

#: rank trace file grammar — the writer and the merge must agree, and a
#: format change must break loudly in one place
RANK_TRACE_RE = re.compile(r"^rank(\d+)\.trace\.jsonl$")

#: the SLO histograms whose slowest observation per window carries an
#: exemplar trace_id (tools/check_metrics.py lints these against the
#: docs catalogue: each must be a documented histogram)
EXEMPLAR_METRICS = ("serving_request_latency_ms", "executor_step_ms")

#: module-level fast-path switch — instrumented code checks this single
#: boolean before touching the tracer at all (the flight_recorder
#: pattern)
_enabled = False

_m_spans = counter(
    "trace_spans_total",
    "Spans recorded into trace trees. For span-recording paths "
    "(executor, prefetch) this is pre-tail-sampling volume — dropped "
    "traces' spans count too; deferred-assembly traces (serving) only "
    "materialize spans when kept, so a dropped request counts its "
    "root alone")
_m_kept = counter(
    "trace_traces_kept_total",
    "Traces kept by tail sampling, by reason: error (a span errored), "
    "exemplar (slowest SLO observation of its window), slow (slowest-"
    "N reservoir), sampled (deterministic every-Nth)",
    labels=("reason",))
_m_dropped = counter(
    "trace_traces_dropped_total",
    "Completed traces discarded by tail sampling (unremarkable and "
    "outside the sample rate)")
_g_exemplar = gauge(
    "slo_exemplar_ms",
    "Slowest observation of each exemplar SLO metric in the current "
    "window, labeled with the trace_id of the span tree that produced "
    "it — the metrics->traces dereference",
    labels=("metric", "trace_id"))

#: spans one trace may hold before the oldest drop (a long-lived
#: pipeline trace must not grow host memory without bound)
_MAX_SPANS_PER_TRACE = 256

#: how long an unadopted stage note may park before adopt_stage drops
#: it: once the staged arrays are garbage-collected their id()s can be
#: reused, and a stale note matched by a recycled id would misattribute
#: its feed_stage phase to an unrelated step. Staging-to-consumption is
#: normally sub-second; a note this old has no live consumer.
_STAGE_NOTE_TTL_S = 60.0

#: PROCESS-GLOBAL trace-id sequence: ids must stay unique across
#: tracer rebuilds (enable(**kwargs) swaps the Tracer but the gauge
#: series, rank files and rings that reference earlier ids live on —
#: a per-instance counter restarting at 1 would reissue them)
_trace_id_seq = itertools.count(1)


class TraceContext:
    """One in-flight trace: the identity (``trace_id``), the open root
    span, and the spans recorded so far. The context object IS the
    propagation currency — it rides on the request/step/batch objects
    across thread boundaries, and any thread may ``record_span``
    against it (deque.append is GIL-atomic)."""

    __slots__ = ("trace_id", "name", "t0", "attrs", "spans", "_seq",
                 "error", "ended", "keep_reason", "screened")

    ROOT = 1

    def __init__(self, trace_id, name, attrs=None):
        self.trace_id = trace_id
        self.name = name
        self.t0 = time.perf_counter()
        self.attrs = dict(attrs) if attrs else {}
        # a plain list, capped at append time (_MAX_SPANS_PER_TRACE):
        # list.append is the cheapest GIL-atomic recorder there is,
        # and span recording IS the tracing hot path
        self.spans = []
        self._seq = itertools.count(self.ROOT + 1)
        self.error = False
        self.ended = False
        #: force-keep with this reason ("exemplar", "sampled") — set
        #: by record_exemplar / the head-gate screen; overrides the
        #: end_trace verdict for everything but errors
        self.keep_reason = None
        #: True when a tail_candidate screen already consumed this
        #: unit's sampling credit (serving's per-batch head-gate):
        #: end_trace must then never run its own sampling branch, or
        #: screened-in riders would be counted — and sampled — twice
        self.screened = False


class _TraceWriter:
    """Appends kept spans as JSON lines to this rank's trace file. The
    FIRST line of every incarnation is the clock-anchor meta — span
    ``ts`` values are raw ``perf_counter`` seconds, and the anchor
    ``(epoch, perf)`` pair is what lets the merge map this process's
    monotonic clock onto the shared wall-clock timeline (a restarted
    rank appends a fresh meta; the merge applies the latest anchor
    seen)."""

    def __init__(self, dirname, rank, flush_every=128):
        os.makedirs(dirname, exist_ok=True)
        self.path = os.path.join(dirname, f"rank{rank}.trace.jsonl")
        self.epoch0 = time.time()
        self.perf0 = time.perf_counter()
        self._flush_every = int(flush_every)
        self._lock = threading.Lock()
        self._buf = [json.dumps({
            "t": "meta", "rank": int(rank), "pid": os.getpid(),
            "epoch": self.epoch0, "perf": self.perf0, "version": 1})]

    def add(self, span_dicts):
        with self._lock:
            self._buf.extend(json.dumps(d, default=str)
                             for d in span_dicts)
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def flush(self):
        with self._lock:
            self._flush_locked()

    def _flush_locked(self):
        if not self._buf:
            return
        try:
            with open(self.path, "a") as f:
                f.write("\n".join(self._buf) + "\n")
        except OSError:
            pass        # a full disk must not kill serving/training
        self._buf = []


class Tracer:
    """The span recorder: bounded ring + optional jsonl writer +
    tail-sampling policy + exemplar store + the cross-thread
    stage-note mailbox."""

    def __init__(self, capacity=4096, sample_rate=0.05, slow_keep=8,
                 slow_window_s=60.0, exemplar_factor=1.2):
        self.capacity = int(capacity)
        self.sample_rate = float(sample_rate)
        self._sample_every = (int(round(1.0 / self.sample_rate))
                              if self.sample_rate > 0 else 0)
        self.slow_keep = int(slow_keep)
        self.slow_window_s = float(slow_window_s)
        # a fresh exemplar must beat the reigning one by this factor
        # (not by a hair): under a latency ramp every request is a new
        # max, and per-request exemplar churn would defeat the
        # head-gate — updates then happen log-many times per ramp
        self.exemplar_factor = float(exemplar_factor)
        self._ring = collections.deque(maxlen=self.capacity)
        self._writer = None
        self._lock = threading.Lock()
        self._completed = 0
        self._sampled_kept = 0          # credits spent on batch keeps
        self._slow = []                 # [(dur_s, monotonic kept at)]
        self._slow_floor = None         # unlocked pre-screen (None =
        self._slow_prune_at = 0.0       # reservoir not full)
        self._slow_kept = 0             # keeps spent this window
        self._slow_cap_reset = 0.0
        self._exemplars = {}            # metric -> (ms, trace_id, mono)
        self._stage_notes = collections.deque(maxlen=64)
        self._stage_seq = itertools.count()
        self._tls = threading.local()
        # the id prefix makes trace ids unique across ranks and
        # incarnations (rank from the launcher env, pid per process)
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
        rank = rank if rank.isdigit() else "0"
        self._prefix = f"{rank}-{os.getpid():x}-"
        self.rank = int(rank)

    # -- recording (hot path) ----------------------------------------------
    def start_trace(self, name, attrs=None, current=False):
        """Open a trace; the returned context is the propagation
        handle. ``current=True`` additionally marks it as this
        thread's in-flight trace, which is what a postmortem embeds
        (``inflight_report``) — use it for thread-resident work like
        the executor step, not for requests that complete on another
        thread."""
        ctx = TraceContext(self._prefix + format(next(_trace_id_seq),
                                                 "x"), name, attrs)
        if current:
            self._tls.current = ctx
        return ctx

    def record_span(self, ctx, name, t0, t1, parent=None, tid=None,
                    kind="span", status="ok", attrs=None):
        """Record one completed phase ``[t0, t1]`` (perf_counter
        seconds) into ``ctx``'s tree; returns the span id (usable as a
        later span's ``parent``). Defaults: parented to the root,
        attributed to the calling thread.

        Hot path: the span is held as a TUPLE — dicts (and the
        span-count metric) materialize once per trace at ``end_trace``,
        and only kept traces pay the dict conversion at all. Tail
        sampling's whole point is that recording must cost less than
        the phases it measures. A trace past ``_MAX_SPANS_PER_TRACE``
        keeps its FIRST spans and drops the rest (long-lived pipeline
        traces must not grow host memory without bound)."""
        sid = next(ctx._seq)
        if status != "ok":
            ctx.error = True
        if len(ctx.spans) < _MAX_SPANS_PER_TRACE:
            ctx.spans.append(
                (sid, ctx.ROOT if parent is None else parent,
                 name, t0, t1 - t0,
                 threading.get_ident() if tid is None else tid,
                 kind, status, attrs))
        return sid

    @staticmethod
    def _span_dict(trace_id, tup):
        sid, parent, name, t0, dur, tid, kind, status, attrs = tup
        d = {"t": "span", "trace": trace_id, "span": sid,
             "parent": parent, "name": name, "ts": t0, "dur": dur,
             "tid": tid, "kind": kind, "status": status}
        if attrs:
            d["attrs"] = dict(attrs)
        return d

    def end_trace(self, ctx, error=False, assemble=None):
        """Close the root span and run the tail-sampling decision over
        the completed tree: kept trees go to the ring (and the rank
        file when armed), dropped trees vanish. Idempotent per
        context; callers already serialize the end (the serving
        first-delivery-wins event, the executor's single thread), so
        the flag needs no lock. The common verdict — drop — takes NO
        lock at all: the slow-reservoir floor is read unlocked (a
        stale read at worst takes the lock for nothing or skips one
        borderline candidate), and the sampling counter tolerates the
        benign increment race.

        ``assemble(ctx)`` is the DEFERRED-assembly hook: a caller that
        only stamped timestamps on its hot path (the serving
        scheduler/replica) passes a callable that records the span
        tree from those stamps — invoked ONLY when the verdict keeps
        the trace, so the dropped majority never pays span
        construction at all."""
        now = time.perf_counter()
        if ctx.ended:
            return None
        ctx.ended = True
        dur = now - ctx.t0
        err = error or ctx.error
        if getattr(self._tls, "current", None) is ctx:
            self._tls.current = None
        if err:
            reason = "error"
        elif ctx.keep_reason:
            reason = ctx.keep_reason
        else:
            reason = None
            floor = self._slow_floor
            if floor is None or dur > floor \
                    or time.monotonic() > self._slow_prune_at:
                with self._lock:
                    if self._is_slow_locked(dur):
                        reason = "slow"
            if reason is None and not ctx.screened:
                self._completed += 1
                if self._sample_every and self._sampled_kept < \
                        self._completed // self._sample_every:
                    self._sampled_kept += 1
                    reason = "sampled"
        if reason is None:
            _m_spans.inc(len(ctx.spans) + 1)    # +1: the root
            _m_dropped.inc()
            return None
        if assemble is not None:
            try:
                assemble(ctx)
            except Exception:   # telemetry must not break delivery
                pass
        ctx.spans.append(
            (ctx.ROOT, None, ctx.name, ctx.t0, dur,
             threading.get_ident(), "root",
             "error" if err else "ok", ctx.attrs or None))
        _m_spans.inc(len(ctx.spans))
        _m_kept.inc(reason=reason)
        kept = [self._span_dict(ctx.trace_id, t) for t in ctx.spans]
        with self._lock:
            self._ring.extend(kept)
        w = self._writer
        if w is not None:
            w.add(kept)
        return reason

    def _is_slow_locked(self, dur):
        """Slowest-``slow_keep`` reservoir over a rolling window: a
        trace qualifies while the reservoir has room or its duration
        beats the reservoir's minimum. The very first traces of a
        window all qualify — warm-up is the honest cost of not knowing
        the distribution yet. ``_slow_floor`` caches the full
        reservoir's minimum so the drop path can pre-screen without
        the lock (None = reservoir not full, everything qualifies).

        Slow keeps are BUDGETED at ``2 * slow_keep`` per window: under
        a latency ramp (a draining burst, a saturating queue) every
        request is a new top-N-so-far, and an unbudgeted reservoir
        would silently turn tail sampling into keep-everything — the
        exact hot-path cost the sampling exists to avoid. Errors and
        exemplars never draw from this budget."""
        now = time.monotonic()
        if now > self._slow_cap_reset:
            self._slow_cap_reset = now + self.slow_window_s
            self._slow_kept = 0
        if self._slow_kept >= 2 * self.slow_keep:
            return False
        horizon = now - self.slow_window_s
        if self._slow and (now > self._slow_prune_at or
                           min(t for _d, t in self._slow) < horizon):
            self._slow = [(d, t) for d, t in self._slow
                          if t >= horizon]
            if len(self._slow) < self.slow_keep:
                self._slow_floor = None
        # the unlocked drop path re-checks this deadline so a stale
        # floor from a faster era cannot suppress slow-keeps forever
        self._slow_prune_at = now + self.slow_window_s / 2.0
        if len(self._slow) < self.slow_keep:
            self._slow.append((dur, now))
            self._slow_floor = None if len(self._slow) < \
                self.slow_keep else min(d for d, _t in self._slow)
            self._slow_kept += 1
            return True
        floor = min(self._slow)
        if dur > floor[0]:
            self._slow.remove(floor)
            self._slow.append((dur, now))
            self._slow_floor = min(d for d, _t in self._slow)
            self._slow_kept += 1
            return True
        return False

    def tail_candidate(self, metric, value_ms, dur_s, count=1):
        """The head-gate for stamp-based hot paths (the serving
        delivery loop): decide in a handful of UNLOCKED compares
        whether this completed unit of work could possibly be kept —
        head-sampled (the counter consumed here; mark the context
        ``keep_reason="sampled"``), a slow-reservoir candidate, or an
        exemplar candidate for ``metric``. Non-candidates pay nothing
        further: no context, no spans, no verdict — which is what
        keeps tracing unmeasurably cheap at full request rate. A
        candidate that loses the subsequent LOCKED check (borderline
        slow/exemplar) is simply dropped by ``end_trace``; the races
        are benign sampling skew.

        The serving scheduler screens once per MICRO-BATCH (its
        riders share the execute window, and the first rider carries
        the max latency), passing ``count`` = riders so the sampling
        cadence and drop accounting stay per-request.

        Returns "sampled" | "candidate" | None."""
        self._completed += count    # benign race: sampling skew only
        if self._sample_every and self._sampled_kept < \
                self._completed // self._sample_every:
            # kept-vs-target credits: keeping a whole batch spends
            # `count` credits, so the long-run kept-REQUEST fraction
            # stays ~sample_rate whatever the batch sizes
            self._sampled_kept += count
            return "sampled"
        now_m = time.monotonic()
        floor = self._slow_floor
        if floor is None or now_m > self._slow_prune_at \
                or now_m > self._slow_cap_reset:
            return "candidate"
        if dur_s > floor and self._slow_kept < 2 * self.slow_keep:
            # the keep budget gates candidacy too: under a latency
            # ramp EVERY request beats the floor, and screening them
            # in just to drop them at the locked check would put the
            # full trace cost back on the hot path
            return "candidate"
        cur = self._exemplars.get(metric)
        if cur is None or value_ms > cur[0] * self.exemplar_factor \
                or now_m - cur[2] > self.slow_window_s:
            return "candidate"
        _m_dropped.inc(count)
        return None

    # -- exemplars ---------------------------------------------------------
    def record_exemplar(self, metric, value_ms, ctx):
        """Remember ``ctx`` as ``metric``'s exemplar if this
        observation beats the reigning one by ``exemplar_factor`` (or
        the previous exemplar aged out of the window), publish it as
        ``slo_exemplar_ms`` (the superseded trace_id's series is
        REMOVED — label cardinality stays one per metric), and
        force-keep the trace so the dereference never dangles.
        Returns whether this observation became the exemplar."""
        # lock-free fast path: the common observation is NOT a new
        # exemplar (dict read is GIL-atomic; a raced stale read at
        # worst re-checks under the lock below)
        now = time.monotonic()
        cur = self._exemplars.get(metric)
        if cur is not None and now - cur[2] <= self.slow_window_s \
                and value_ms <= cur[0] * self.exemplar_factor:
            return False
        trace_id = ctx.trace_id if isinstance(ctx, TraceContext) \
            else str(ctx)
        with self._lock:
            cur = self._exemplars.get(metric)
            if cur is not None and now - cur[2] <= self.slow_window_s \
                    and value_ms <= cur[0] * self.exemplar_factor:
                return False
            if cur is not None and cur[1] != trace_id:
                _g_exemplar.remove(metric=metric, trace_id=cur[1])
            self._exemplars[metric] = (float(value_ms), trace_id, now)
            # publish INSIDE the lock: an unlocked set racing a
            # concurrent supersession could resurrect a removed
            # trace_id series forever (the gauge's own lock nests
            # under this one; nothing takes them in reverse order)
            _g_exemplar.set(float(value_ms), metric=metric,
                            trace_id=trace_id)
        if isinstance(ctx, TraceContext):
            ctx.keep_reason = "exemplar"
        return True

    def exemplars(self):
        """{metric: (value_ms, trace_id)} — the current window's
        slowest observation per exemplar metric."""
        with self._lock:
            return {m: (v, t) for m, (v, t, _at) in
                    self._exemplars.items()}

    # -- cross-thread stage mailbox ----------------------------------------
    def stage_note(self, name, t0, t1, tid=None, attrs=None,
                   key=None):
        """A producer-thread phase (feed staging in a prefetch worker)
        whose consuming trace does not exist yet: park it here; the
        consumer adopts it into its trace with ``adopt_stage``.
        ``key`` is the set of ``id()``s of the staged arrays — the
        identity the consuming step matches against, so a note can
        only ever land in the tree of the step that actually consumes
        those arrays."""
        d = dict(attrs or {})
        d["stage_seq"] = next(self._stage_seq)
        # the trailing parked-at stamp (NOT t1, which callers may
        # backfill) is what adopt_stage ages the note out by
        note = (name, t0, t1,
                threading.get_ident() if tid is None else tid, d,
                frozenset(key) if key is not None else None,
                time.perf_counter())
        # locked: adopt_stage iterates this deque from the consumer
        # thread while prefetch workers append — an unlocked append
        # mid-iteration raises "deque mutated during iteration" there
        with self._lock:
            self._stage_notes.append(note)

    def adopt_stage(self, ctx, match=None):
        """Adopt a parked stage note as a span of ``ctx`` — the
        cross-thread parenting move: the span executed on the worker
        thread (its tid says so) but belongs to this step's tree.
        With ``match`` (the consuming step's feed-array ids) only the
        note whose staged arrays THIS step consumes is adopted —
        an interleaved manually-fed step can neither steal a
        pipeline's note nor shift later adoptions off by one. Without
        ``match``, FIFO. Returns the span id or None."""
        with self._lock:
            # age out notes nobody adopted (an abandoned pipeline):
            # the staged arrays are gone and CPython may reuse their
            # ids, so a lingering note could otherwise be adopted into
            # an unrelated later step's tree. FIFO by parked-at, so
            # popping stale heads bounds the lingering window.
            horizon = time.perf_counter() - _STAGE_NOTE_TTL_S
            while self._stage_notes and self._stage_notes[0][6] < horizon:
                self._stage_notes.popleft()
            if match is None:
                try:
                    note = self._stage_notes.popleft()
                except IndexError:
                    return None
            else:
                note = None
                for n in self._stage_notes:
                    if n[5] is not None and not n[5].isdisjoint(match):
                        note = n
                        break
                if note is None:
                    return None
                self._stage_notes.remove(note)
        name, t0, t1, tid, attrs, _key, _parked = note
        return self.record_span(ctx, name, t0, t1, tid=tid,
                                attrs=attrs)

    # -- inspection --------------------------------------------------------
    def inflight_report(self):
        """The calling thread's in-flight trace (opened with
        ``current=True``) as a postmortem-embeddable dict, or None.
        This is what lets ``anomaly.trip()`` name the PHASE a dying
        step was in, not just the step number."""
        ctx = getattr(self._tls, "current", None)
        if ctx is None or ctx.ended:
            return None
        return {"trace_id": ctx.trace_id, "root": ctx.name,
                "age_s": round(time.perf_counter() - ctx.t0, 6),
                "attrs": dict(ctx.attrs),
                "spans": [self._span_dict(ctx.trace_id, t)
                          for t in list(ctx.spans)[-32:]]}

    def spans(self, trace_id=None):
        """Kept spans from the ring (newest last), optionally filtered
        to one trace. Snapshot under the lock — a replica thread
        extending the ring mid-iteration would otherwise raise."""
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s["trace"] == trace_id]
        return out

    # -- arming ------------------------------------------------------------
    def install(self, dirname):
        """Arm the jsonl writer under ``dirname`` for this rank. An
        already-armed writer is flushed before being replaced — a
        re-arm (enable(d) twice, or install_from_env after a manual
        enable) must not drop its buffered span lines on the floor."""
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
        old = self._writer
        if old is not None:
            old.flush()
        self._writer = _TraceWriter(
            dirname, rank if rank.isdigit() else "0")
        return self._writer.path

    def flush(self):
        w = self._writer
        if w is not None:
            w.flush()


#: process-wide default tracer the instrumented layers feed
TRACER = Tracer()

_atexit_registered = False


def enable(dirname=None, **kwargs):
    """Turn tracing on. ``kwargs`` (capacity / sample_rate / slow_keep
    / slow_window_s / exemplar_factor) rebuild the tracer with that
    policy — the installed writer and the exemplar bookkeeping CARRY
    OVER (an armed worker adjusting its sampling policy must not
    silently stop streaming to its rank file, and the reigning
    ``slo_exemplar_ms`` series must stay removable when superseded).
    With a ``dirname`` kept traces also stream to
    ``<dirname>/rank<N>.trace.jsonl`` (flushed at exit)."""
    global _enabled, TRACER, _atexit_registered
    if kwargs:
        old = TRACER
        TRACER = Tracer(**kwargs)
        TRACER._writer = old._writer
        TRACER._exemplars = dict(old._exemplars)
    _enabled = True
    if dirname:
        TRACER.install(dirname)
        if not _atexit_registered:
            import atexit
            _atexit_registered = True
            atexit.register(flush)
    return TRACER


def disable():
    """Turn tracing off, flush any buffered file lines (so a test or
    an operator can read the rank file immediately), and drop parked
    stage notes — a note surviving a disable/enable cycle would be
    adopted by an unrelated later step."""
    global _enabled
    _enabled = False
    with TRACER._lock:
        TRACER._stage_notes.clear()
    TRACER.flush()


def is_enabled():
    return _enabled


def install_from_env(env=None):
    """Worker-side hookup: arm tracing iff the launcher exported
    PADDLE_TRACE_DIR (sampling knobs PADDLE_TRACE_SAMPLE /
    PADDLE_TRACE_SLOW_KEEP ride the same env). Returns the tracer or
    None."""
    env = os.environ if env is None else env
    d = env.get(ENV_DIR)
    if not d:
        return None
    kw = {}
    # malformed knobs fall back to defaults: this runs inside
    # auto_checkpoint's startup wiring, and the tracing stack is
    # never-fail — a typo'd sample rate must not kill the worker
    if env.get(ENV_SAMPLE):
        try:
            kw["sample_rate"] = float(env[ENV_SAMPLE])
        except ValueError:
            pass
    if env.get(ENV_SLOW_KEEP):
        try:
            kw["slow_keep"] = int(env[ENV_SLOW_KEEP])
        except ValueError:
            pass
    return enable(d, **kw)


# module-level conveniences over the default tracer (mirror the
# flight_recorder surface; instrumented code guards on `_enabled`)
def start_trace(name, attrs=None, current=False):
    return TRACER.start_trace(name, attrs=attrs, current=current)


def end_trace(ctx, error=False, assemble=None):
    return TRACER.end_trace(ctx, error=error, assemble=assemble)


def record_span(ctx, name, t0, t1, parent=None, tid=None, kind="span",
                status="ok", attrs=None):
    return TRACER.record_span(ctx, name, t0, t1, parent=parent,
                              tid=tid, kind=kind, status=status,
                              attrs=attrs)


def tail_candidate(metric, value_ms, dur_s, count=1):
    return TRACER.tail_candidate(metric, value_ms, dur_s, count)


def record_exemplar(metric, value_ms, ctx):
    return TRACER.record_exemplar(metric, value_ms, ctx)


def stage_note(name, t0, t1, tid=None, attrs=None, key=None):
    return TRACER.stage_note(name, t0, t1, tid=tid, attrs=attrs,
                             key=key)


def adopt_stage(ctx, match=None):
    return TRACER.adopt_stage(ctx, match=match)


def inflight_report():
    return TRACER.inflight_report()


def spans(trace_id=None):
    return TRACER.spans(trace_id=trace_id)


def flush():
    TRACER.flush()


# -- cross-rank merge (launcher side, stdlib-only) ---------------------------
def _read_rank_file(path):
    """Yield (epoch_ts, span_dict) for every clock-aligned span line.
    Span ``ts`` values are raw perf_counter seconds; the latest meta
    anchor seen maps them onto the wall-clock timeline (a restarted
    incarnation appends a fresh anchor mid-file). Torn trailing lines
    (a killed rank mid-write) and pre-anchor spans are skipped — merge
    is a best-effort evidence reader, like the postmortem path."""
    anchor = None
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if d.get("t") == "meta":
                anchor = (float(d["epoch"]), float(d["perf"]))
            elif d.get("t") == "span" and anchor is not None:
                yield anchor[0] + (float(d["ts"]) - anchor[1]), d


def merge_rank_traces(traces_dir, out_path=None):
    """Merge every ``rank<N>.trace.jsonl`` under ``traces_dir`` into
    ONE Chrome-trace/Perfetto JSON (default ``<parent>/trace.json``):
    one pid per rank, thread metadata, X slices carrying
    trace/span/parent ids + attrs in ``args``, and flow arrows for
    cross-thread parent->child hops (the batcher->replica and
    prefetch-worker->step hand-offs). Clock alignment: each rank's
    monotonic timestamps are mapped through its own (epoch, perf)
    anchor, so ranks with arbitrary perf_counter origins land on one
    shared timeline. Returns the output path, or None when there is
    nothing to merge."""
    try:
        names = sorted(os.listdir(traces_dir))
    except OSError:
        return None
    files = [(int(m.group(1)), os.path.join(traces_dir, fn))
             for fn in names for m in [RANK_TRACE_RE.match(fn)] if m]
    if not files:
        return None
    all_spans = []                  # (rank, epoch_ts, span_dict)
    for rank, path in files:
        try:
            for ets, d in _read_rank_file(path):
                all_spans.append((rank, ets, d))
        except OSError:
            continue
    if not all_spans:
        return None
    t0 = min(ets for _r, ets, _d in all_spans)
    events = []
    tid_map = {}                    # (rank, raw tid) -> small int
    index = {}                      # (rank, trace, span) -> (ts_us, tid)
    ranks = sorted({r for r, _e, _d in all_spans})
    for r in ranks:
        events.append({"name": "process_name", "ph": "M", "pid": r,
                       "args": {"name": f"rank {r}"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": r, "args": {"sort_index": r}})
    for rank, ets, d in all_spans:
        key = (rank, d.get("tid"))
        if key not in tid_map:
            tid_map[key] = len([k for k in tid_map if k[0] == rank])
            events.append({"name": "thread_name", "ph": "M",
                           "pid": rank, "tid": tid_map[key],
                           "args": {"name":
                                    f"thread {d.get('tid')}"}})
        ts_us = (ets - t0) * 1e6
        args = {"trace": d.get("trace"), "span": d.get("span"),
                "parent": d.get("parent"),
                "status": d.get("status", "ok")}
        args.update(d.get("attrs") or {})
        events.append({
            "name": d.get("name", "?"), "ph": "X",
            "cat": d.get("kind", "span"), "ts": ts_us,
            "dur": float(d.get("dur", 0.0)) * 1e6,
            "pid": rank, "tid": tid_map[key], "args": args,
        })
        index[(rank, d.get("trace"), d.get("span"))] = \
            (ts_us, tid_map[key])
    # flow arrows: a span whose PARENT ran on a different thread is a
    # causal hand-off the timeline should draw (contexts never cross
    # ranks, so flows stay within one pid)
    flow_id = 0
    for rank, ets, d in all_spans:
        parent = d.get("parent")
        if parent is None:
            continue
        src = index.get((rank, d.get("trace"), parent))
        child_tid = tid_map[(rank, d.get("tid"))]
        if src is None or src[1] == child_tid:
            continue
        flow_id += 1
        ts_us = (ets - t0) * 1e6
        events.append({"name": "handoff", "ph": "s", "cat": "flow",
                       "id": flow_id, "ts": src[0], "pid": rank,
                       "tid": src[1]})
        events.append({"name": "handoff", "ph": "f", "bp": "e",
                       "cat": "flow", "id": flow_id, "ts": ts_us,
                       "pid": rank, "tid": child_tid})
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(traces_dir)), "trace.json")
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, out_path)
    return out_path


def main(argv=None):      # pragma: no cover - thin CLI over the merge
    import argparse
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.monitor.trace",
        description="merge per-rank trace jsonl files into one "
                    "Perfetto/Chrome trace JSON")
    ap.add_argument("traces_dir",
                    help="directory holding rank<N>.trace.jsonl files "
                         "(the launcher writes <log_dir>/traces)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <parent>/trace.json)")
    args = ap.parse_args(argv)
    out = merge_rank_traces(args.traces_dir, args.out)
    if out is None:
        print("no rank trace files found")
        return 1
    print(out)
    return 0


if __name__ == "__main__":        # pragma: no cover
    raise SystemExit(main())
