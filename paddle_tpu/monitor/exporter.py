"""Prometheus text-format export + per-rank snapshot files + job view.

Three consumers, one format:

- **In-process scrape**: ``MetricsServer`` serves ``GET /metrics`` from
  a stdlib ``http.server`` daemon thread (no new deps, off by default).
- **Per-rank snapshot files**: ``RankExporter`` writes the registry as
  Prometheus text next to this rank's heartbeat file
  (``<heartbeat_dir>/rank<N>.prom``, see ``distributed/health.py``)
  on a background thread. Writes are ATOMIC (tmp + ``os.replace``) and
  end with an ``# EOF`` marker, so a concurrent reader either sees a
  complete snapshot or — if it insists on reading mid-replace on a
  filesystem without atomic rename — detects the tear by the missing
  marker. ``parse_text`` refuses marker-less input for exactly that
  reason.
- **Job-level view**: the elastic launcher merges every rank's snapshot
  (sum for counters/histograms, max for gauges — summing a per-rank
  FLOPs gauge across replicas would double-count work) into
  ``<log_dir>/metrics.prom`` and a one-line status log
  (``step=… ms/step=… mfu=… restarts=…``).
"""

import os
import re
import threading

from paddle_tpu.monitor.httpd import ThreadedHTTPServerBase
from paddle_tpu.monitor.registry import REGISTRY, counter

__all__ = [
    "render_text", "write_snapshot", "parse_text", "aggregate",
    "read_rank_snapshots", "write_job_snapshot", "job_status_line",
    "RankExporter", "MetricsServer", "EOF_MARKER", "CONTENT_TYPE",
]

EOF_MARKER = "# EOF"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _esc(v):
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v):
    f = float(v)
    if f != f:
        return "NaN"                 # repr() would emit 'nan', which
    if f == float("inf"):            # the parser (rightly) rejects
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _labelstr(labelnames, key, extra=()):
    pairs = [f'{n}="{_esc(v)}"' for n, v in zip(labelnames, key)]
    pairs += [f'{n}="{_esc(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_text(registry=None):
    """The whole registry as Prometheus exposition text (0.0.4),
    terminated by the ``# EOF`` torn-read marker."""
    registry = registry or REGISTRY
    lines = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if m.kind == "histogram":
            for key, (cum, total, count) in sorted(m.samples().items()):
                les = [_fmt(b) for b in m.buckets] + ["+Inf"]
                for le, c in zip(les, cum):
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_labelstr(m.labelnames, key, [('le', le)])}"
                        f" {_fmt(c)}")
                ls = _labelstr(m.labelnames, key)
                lines.append(f"{m.name}_sum{ls} {_fmt(total)}")
                lines.append(f"{m.name}_count{ls} {_fmt(count)}")
        else:
            for key, v in sorted(m.samples().items()):
                lines.append(
                    f"{m.name}{_labelstr(m.labelnames, key)} {_fmt(v)}")
    lines.append(EOF_MARKER)
    return "\n".join(lines) + "\n"


def _atomic_write(path, text):
    """tmp + ``os.replace``; the tmp name is unique per call (mkstemp),
    so two threads publishing the same path can never interleave writes
    into one tmp file — last replace wins, both complete."""
    import tempfile
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path),
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def write_snapshot(path, registry=None):
    """Atomically publish the registry as text at ``path``: a reader
    never sees a torn snapshot."""
    return _atomic_write(path, render_text(registry))


# -- parsing / aggregation (launcher side) ----------------------------------
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][\w:]*) (\w+)\s*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][\w:]*)(?:\{(.*)\})?\s+"
    r"(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|[+-]Inf|NaN)\s*$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][\w]*)="((?:[^"\\]|\\.)*)"')


def _unesc(v):
    # single left-to-right pass (sequential .replace would corrupt a
    # literal backslash-n that was escaped as \\n)
    return re.sub(r"\\(.)",
                  lambda m: "\n" if m.group(1) == "n" else m.group(1),
                  v)


def parse_text(text):
    """Parse exposition text into ``(types, samples)``:
    ``types[name] = kind``; ``samples[(name, labelpairs)] = value``
    where ``labelpairs`` is a sorted tuple of (label, value).

    Raises ValueError when the ``# EOF`` marker is missing — the torn-
    snapshot guard the atomic-write contract promises readers."""
    lines = text.splitlines()
    if EOF_MARKER not in (ln.strip() for ln in lines):
        raise ValueError("snapshot missing '# EOF' marker (torn read?)")
    types, samples = {}, {}
    for ln in lines:
        if ln.startswith("#"):
            m = _TYPE_RE.match(ln)
            if m:
                types[m.group(1)] = m.group(2)
            continue
        if not ln.strip():
            continue
        m = _SAMPLE_RE.match(ln)
        if not m:
            raise ValueError(f"unparseable metrics line: {ln!r}")
        name, labelblob, val = m.groups()
        pairs = tuple(sorted(
            (k, _unesc(v))
            for k, v in _LABEL_PAIR_RE.findall(labelblob or "")))
        samples[(name, pairs)] = float(
            val.replace("+Inf", "inf").replace("-Inf", "-inf"))
    return types, samples


def _base_name(name, types):
    """Histogram sample names carry _bucket/_sum/_count suffixes; map
    back to the declared metric for type lookup."""
    if name in types:
        return name
    for suf in ("_bucket", "_sum", "_count"):
        if name.endswith(suf) and name[:-len(suf)] in types:
            return name[:-len(suf)]
    return name


#: series that take MAX across snapshots even though typed counter:
#: every rank reports its incarnation index and the launcher counts the
#: same restart events — summing would report one gang restart of N
#: ranks as N+1 restarts
_MAX_MERGE_NAMES = frozenset({"restarts_total"})

#: gauges that take MIN across snapshots: train_health is 1=healthy /
#: 0=tripped, and the job is only as healthy as its sickest rank — the
#: default max-merge would report a job with one anomalous rank as
#: healthy in <log_dir>/metrics.prom
_MIN_MERGE_NAMES = frozenset({"train_health"})


def aggregate(parsed):
    """Merge a list of ``(types, samples)`` into one job-level view:
    counters and histogram series SUM across ranks; gauges — and the
    restart count, which every party reports for the same events — take
    the MAX (per-rank FLOPs/queue-depth summed over replicas would read
    as more work than any rank did); health-style gauges where the job
    is only as good as its worst rank (``train_health``) take the
    MIN."""
    types, samples = {}, {}
    for t, s in parsed:
        types.update(t)
    for t, s in parsed:
        for key, v in s.items():
            kind = types.get(_base_name(key[0], types), "counter")
            if key not in samples:
                samples[key] = v
            elif key[0] in _MIN_MERGE_NAMES:
                samples[key] = min(samples[key], v)
            elif kind == "gauge" or key[0] in _MAX_MERGE_NAMES:
                samples[key] = max(samples[key], v)
            else:
                samples[key] += v
    return types, samples


def render_parsed(types, samples):
    """Aggregated (types, samples) back to exposition text."""
    lines, seen = [], set()
    for (name, pairs) in sorted(samples):
        base = _base_name(name, types)
        if base not in seen and base in types:
            seen.add(base)
            lines.append(f"# TYPE {base} {types[base]}")
        ls = "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in pairs) + "}" \
            if pairs else ""
        lines.append(f"{name}{ls} {_fmt(samples[(name, pairs)])}")
    lines.append(EOF_MARKER)
    return "\n".join(lines) + "\n"


_RANK_SNAP_RE = re.compile(r"^rank(\d+)\.prom$")


def read_rank_snapshots(dirname):
    """{rank: (types, samples)} for every readable, untorn
    ``rank<N>.prom`` in ``dirname`` (torn/missing files are skipped —
    the next exporter tick replaces them)."""
    out = {}
    try:
        names = os.listdir(dirname)
    except OSError:
        return out
    for fn in names:
        m = _RANK_SNAP_RE.match(fn)
        if not m:
            continue
        try:
            with open(os.path.join(dirname, fn)) as f:
                out[int(m.group(1))] = parse_text(f.read())
        except (OSError, ValueError):
            continue
    return out


def write_job_snapshot(hb_dir, out_path, registry=None, snaps=None):
    """Aggregate every rank's snapshot (plus ``registry`` — the
    launcher's own restart/watchdog counters) into one atomic file.
    Returns ``out_path``, or None when there is nothing to write.
    Pass pre-read ``snaps`` to reuse one directory scan and keep the
    written aggregate consistent with whatever the caller just judged
    (the launcher's status tick does)."""
    if snaps is None:
        snaps = read_rank_snapshots(hb_dir)
    parsed = list(snaps.values())
    if registry is not None:
        parsed.append(parse_text(render_text(registry)))
    if not parsed:
        return None
    return _atomic_write(out_path, render_parsed(*aggregate(parsed)))


def _sum_matching(samples, name):
    return sum(v for (n, _), v in samples.items() if n == name)


def _max_matching(samples, name):
    return max((v for (n, _), v in samples.items() if n == name),
               default=0.0)


def job_status_line(hb_dir, restarts=0, snaps=None, health=None,
                    registry=None):
    """The launcher's periodic one-liner:
    ``step=… ms/step=… mem=…/…GB mfu=… goodput=…% health=… ranks=…
    restarts=…`` computed from the rank snapshots in ``hb_dir``; None
    when no rank has exported yet. ``mem`` (worst device's high-water
    mark over the known limit, monitor/memory.py) appears only once
    some rank's memory poller has sampled; ``goodput`` (device-compute
    share of all ledger-attributed seconds, monitor/goodput.py) only
    once some party's ledger is armed.

    ``step`` is the max across ranks (they advance together in data
    parallel); ms/step pools every rank's histogram; mfu uses the
    max-across-ranks per-step FLOPs (see ``monitor.cost`` for the
    peak-FLOPs source and its CPU-host caveats); ``health`` comes from
    ``monitor.anomaly.job_health`` — anomaly trips any rank exported
    plus step-time-skew straggler detection over the same snapshots.
    Pass pre-read ``snaps`` and a pre-computed ``health`` string to
    reuse one directory scan / one job_health judgment (the launcher's
    status tick does, so its log line and straggler bookkeeping judge
    the SAME snapshot state with the SAME skew threshold). Every field
    of one line derives from that single read — mem/health/goodput in
    one tick can never disagree about which snapshots they judged.
    ``registry`` (the launcher passes its own) joins the aggregation
    so launcher-side ledger phases (``restart_downtime``) count in the
    goodput denominator; the computed fraction is published back to it
    as the ``goodput_fraction`` gauge, which the subsequent
    ``write_job_snapshot(registry=...)`` then carries into
    <log_dir>/metrics.prom."""
    if snaps is None:
        snaps = read_rank_snapshots(hb_dir)
    if not snaps:
        return None
    step = 0
    flops = 0.0
    for _, (types, samples) in snaps.items():
        step = max(step, int(_sum_matching(samples,
                                           "executor_steps_total")))
        flops = max(flops, _sum_matching(samples, "segment_flops"))
    parsed = list(snaps.values())
    if registry is not None:
        parsed.append(parse_text(render_text(registry)))
    _, merged = aggregate(parsed)
    ms_sum = _sum_matching(merged, "executor_step_ms_sum")
    ms_count = _sum_matching(merged, "executor_step_ms_count")
    ms = ms_sum / ms_count if ms_count else 0.0
    parts = [f"step={step}", f"ms/step={ms:.1f}"]
    # worst device's high-water mark across ranks, off the SAME merged
    # view as every other field (gauges max-merge, and the launcher
    # sweeps departed ranks' files, so no stale rank pins the number):
    # mem=<high-water>/<limit>GB, limit part only when some rank knows
    # one (monitor/memory.py poller)
    hwm = _max_matching(merged, "hbm_bytes_high_water")
    if hwm > 0:
        limit = _max_matching(merged, "hbm_bytes_limit")
        gb = 1024.0 ** 3
        mem = f"mem={hwm / gb:.2f}"
        if limit > 0:
            mem += f"/{limit / gb:.2f}"
        parts.append(mem + "GB")
    if flops > 0 and ms > 0:
        from paddle_tpu.monitor.cost import peak_flops
        mfu = flops / (ms / 1e3) / peak_flops()
        parts.append(f"mfu={mfu:.4f}")
    from paddle_tpu.monitor import goodput as _goodput
    frac = _goodput.fraction_of(merged)
    if frac is not None:
        parts.append(f"goodput={frac * 100.0:.0f}%")
        if registry is not None:
            _goodput._g_fraction.set(frac)
    if health is None:
        from paddle_tpu.monitor import anomaly as _anomaly
        health, _stragglers = _anomaly.job_health(snaps)
    parts.append(f"health={health}")
    parts.append(f"ranks={len(snaps)}")
    parts.append(f"restarts={restarts}")
    return " ".join(parts)


# -- per-rank background exporter -------------------------------------------
class RankExporter:
    """Writes the registry to ``path`` every ``interval`` seconds on a
    daemon thread (plus once on ``stop()``, so a clean exit always
    leaves a final snapshot). ``from_env()`` is the launcher hookup:
    under ``paddle_tpu.distributed.launch`` the snapshot lands next to
    this rank's heartbeat file, where the launcher aggregates it."""

    def __init__(self, path, interval=2.0, registry=None):
        self.path = path
        self.interval = float(interval)
        self.registry = registry or REGISTRY
        self._stop = threading.Event()
        self._thread = None

    @classmethod
    def from_env(cls, env=None, interval=2.0, registry=None):
        """A RankExporter wired from the launcher's env (None when not
        launched under a supervisor). Also registers this incarnation's
        ``restarts_total`` from PADDLE_RESTART_COUNT, so a restarted
        rank's snapshot carries its restart count."""
        from paddle_tpu.distributed import health
        env = os.environ if env is None else env
        if not env.get(health.ENV_DIR):
            return None
        rank = env.get(health.ENV_RANK, "0")
        path = health.metrics_path(env[health.ENV_DIR], rank)
        exp = cls(path, interval=interval, registry=registry)
        restarts = counter(
            "restarts_total",
            "Restarts: the launcher counts restarts it performed; a "
            "rank reports its own incarnation index",
            registry=exp.registry)
        restarts.inc(int(env.get("PADDLE_RESTART_COUNT", "0") or 0))
        return exp

    def write_now(self):
        try:
            return write_snapshot(self.path, self.registry)
        except OSError:
            return None     # a full disk must not kill the loop

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="pt-rank-exporter")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            self.write_now()

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
        self.write_now()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# -- optional /metrics endpoint ---------------------------------------------
class MetricsServer(ThreadedHTTPServerBase):
    """``GET /metrics`` over the shared threaded-HTTP base
    (``monitor/httpd.py``) on a daemon thread. ``port=0`` picks a free
    port (read ``self.port`` after ``start()``). Loopback-only by
    default: metrics can leak shapes and step counts, so exposing
    beyond the host is an explicit choice. ``socket_timeout_s`` bounds
    every socket read/write per connection, so a scraper that connects
    and then stalls can no longer pin a handler thread forever."""

    thread_name = "pt-metrics-server"

    def __init__(self, port=0, host="127.0.0.1", registry=None,
                 socket_timeout_s=10.0):
        super().__init__(port=port, host=host,
                         socket_timeout_s=socket_timeout_s)
        self.registry = registry or REGISTRY

    def _handler_class(self):
        import http.server

        registry = self.registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = render_text(registry).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):    # quiet: no per-scrape stderr
                pass

        return Handler
