"""Device-memory observability: compile-time ledger, runtime HBM
accounting, and OOM postmortems.

``cost.py`` answers "how much of the hardware did we use";
this module answers "how much of the hardware do we OCCUPY" — the
missing third axis of the observability spine (metrics, numerics/
tracing, memory). Four layers:

- **Compile-time ledger.** ``analyze_compiled(compiled)`` reads jax's
  ``compiled.memory_analysis()`` (XLA's ``CompiledMemoryStats``:
  argument/output/temp/alias/generated-code bytes — the memory analog
  of ``cost_analysis``) for each AOT-compiled device segment; the
  executor records them here (``record_segment_memory``) at
  AOT-compile time, serving records per-bucket executables, and the
  ``memory_ledger_bytes`` gauge attributes resident bytes to *named
  entities* (params, optimizer slots, serving buckets, cache pools)
  via ``ledger_set``. Capture happens ONLY where a compiled executable
  is already in hand — compiling a lowering solely to ask its memory
  footprint would double first-step compile cost.
- **Runtime accounting.** ``enable(interval)`` starts a sampled
  live-buffer poller: ``jax.live_arrays()`` aggregated by device into
  ``hbm_bytes_in_use`` / ``hbm_bytes_limit`` / ``hbm_utilization``
  gauges plus a high-water mark (``high_water``) the launcher status
  line reports as ``mem=…/…GB``. ``disable()`` == zero recording: no
  thread, no samples, no gauge series.
- **OOM postmortem.** ``is_oom_error`` recognizes XLA's
  RESOURCE_EXHAUSTED at the executor-dispatch and serving-replica
  boundaries; ``handle_oom`` converts it to a typed
  ``OutOfDeviceMemoryError`` carrying ``oom_postmortem()`` (ledger
  table, top-K live buffers with shapes/dtypes, the segment's
  compile-time estimate vs the limit) and dumps it through
  ``anomaly.trip("oom")`` → flight recorder (which embeds the
  in-flight trace when tracing is armed).
- **Admission.** ``admission_headroom(projected)`` is the arithmetic
  ``serving/swap.py`` consults before booting a standby pool: refuse
  with projected numbers instead of discovering a mid-cutover OOM.

``hbm_bytes_limit`` comes from ``device.memory_stats()`` when the
backend reports one (TPU/GPU) else the ``PADDLE_TPU_HBM_LIMIT_BYTES``
env override (CPU hosts report none — the utilization gauge stays
unset there unless the override is given). jax is only imported
inside functions: this module loads under the stdlib-only launcher.
"""

import os
import threading
import time

from paddle_tpu.monitor.registry import counter, gauge

__all__ = [
    "analyze_compiled", "record_segment_memory", "memory_segments",
    "peak_bytes_per_step", "ledger_set", "ledger_remove", "ledger",
    "ledger_table", "enable", "disable", "poller_enabled",
    "sample_now", "high_water", "hbm_limit_bytes",
    "hbm_utilization_max", "device_usage", "top_live_buffers",
    "OutOfDeviceMemoryError", "is_oom_error", "oom_postmortem",
    "handle_oom", "admission_headroom", "summary_line", "reset",
]

#: env override for the per-device HBM capacity when the backend
#: reports no memory_stats (CPU hosts); also the serving admission
#: limit fallback when ServingConfig.hbm_limit_bytes is unset
HBM_LIMIT_ENV = "PADDLE_TPU_HBM_LIMIT_BYTES"

_lock = threading.Lock()
_segments = {}            # group -> {index: {"temp_bytes", ...}}
_latest_group = None
_ledger = {}              # entity -> bytes
_high_water = {}          # device label -> peak observed in-use bytes

_g_temp = gauge(
    "segment_temp_bytes",
    "Compile-time temp-buffer bytes XLA reserves per execution of each "
    "compiled device segment (scratch/workspace from "
    "compiled.memory_analysis)", labels=("segment",))
_g_arg = gauge(
    "segment_argument_bytes",
    "Compile-time argument-buffer bytes of each compiled device "
    "segment (inputs resident for the call, from memory_analysis)",
    labels=("segment",))
_g_peak = gauge(
    "segment_peak_bytes_estimate",
    "Compile-time peak device bytes estimate per execution of each "
    "compiled segment (argument + output + temp - aliased + generated "
    "code)", labels=("segment",))
_g_ledger = gauge(
    "memory_ledger_bytes",
    "Resident device/host bytes the memory ledger attributes to each "
    "named entity (params, optimizer slots, serving buckets, cache "
    "pools)", labels=("entity",))
_g_in_use = gauge(
    "hbm_bytes_in_use",
    "Live device-buffer bytes per device, sampled by the memory "
    "poller from jax.live_arrays aggregation", labels=("device",))
_g_limit = gauge(
    "hbm_bytes_limit",
    "Device memory capacity bytes per device (backend memory_stats "
    "when reported, else the PADDLE_TPU_HBM_LIMIT_BYTES override)",
    labels=("device",))
_g_util = gauge(
    "hbm_utilization",
    "hbm_bytes_in_use / hbm_bytes_limit per device, in [0, 1]; unset "
    "when no limit is known (CPU host without the env override)",
    labels=("device",))
_g_hwm = gauge(
    "hbm_bytes_high_water",
    "Peak hbm_bytes_in_use observed per device since process start "
    "(or the last reset) — the capacity-planning bytes number",
    labels=("device",))
_c_oom = counter(
    "oom_errors_total",
    "RESOURCE_EXHAUSTED device allocations converted to typed "
    "OutOfDeviceMemoryError postmortems, by boundary",
    labels=("where",))


def analyze_compiled(compiled):
    """{'argument_bytes', 'output_bytes', 'temp_bytes',
    'generated_code_bytes', 'alias_bytes', 'peak_bytes_estimate'} from
    a ``jax.stages.Compiled`` (XLA ``CompiledMemoryStats``), or None
    when the backend offers none. The peak estimate is the sum of what
    must co-reside during one execution: arguments + outputs + temps
    - aliased (donated buffers counted once) + generated code."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def _b(attr):
        try:
            return float(getattr(ma, attr, 0) or 0)
        except Exception:
            return 0.0

    arg = _b("argument_size_in_bytes")
    out = _b("output_size_in_bytes")
    tmp = _b("temp_size_in_bytes")
    alias = _b("alias_size_in_bytes")
    gen = _b("generated_code_size_in_bytes")
    if not any((arg, out, tmp, gen)):
        return None
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        "generated_code_bytes": gen,
        "peak_bytes_estimate": max(0.0, arg + out + tmp - alias + gen),
    }


def record_segment_memory(group, index, analysis):
    """Record one device segment's compile-time memory analysis under
    ``group`` (an identity for the compiled step, e.g. ``id(step)``).
    Same latest-group-wins gauge semantics as ``cost.record_segment``:
    the gauges mirror ONLY the most recent group, so a retrace can't
    leave stale segment series inflating sums."""
    global _latest_group
    if not analysis:
        return
    with _lock:
        if group != _latest_group:
            _g_temp.clear()
            _g_arg.clear()
            _g_peak.clear()
        _segments.setdefault(group, {}).setdefault(
            int(index), {}).update(analysis)
        _latest_group = group
    seg = str(index)
    _g_temp.set(analysis.get("temp_bytes", 0.0), segment=seg)
    _g_arg.set(analysis.get("argument_bytes", 0.0), segment=seg)
    _g_peak.set(analysis.get("peak_bytes_estimate", 0.0), segment=seg)


def memory_segments(group=None):
    """{segment index: analysis dict} for ``group`` (default: the most
    recently recorded compiled step)."""
    with _lock:
        g = _latest_group if group is None else group
        return {i: dict(a) for i, a in _segments.get(g, {}).items()}


def peak_bytes_per_step():
    """Max compile-time peak estimate across the latest compiled
    step's segments (segments execute sequentially, so the step's peak
    is the worst segment, not the sum)."""
    with _lock:
        segs = _segments.get(_latest_group, {})
        return max((a.get("peak_bytes_estimate", 0.0)
                    for a in segs.values()), default=0.0)


# -- ledger ----------------------------------------------------------------

def ledger_set(entity, nbytes):
    """Attribute ``nbytes`` resident bytes to ``entity`` (a stable
    name like ``"train/params"`` or ``"serving/live/bucket8"``);
    publishes/updates the ``memory_ledger_bytes`` series."""
    entity = str(entity)
    with _lock:
        _ledger[entity] = float(nbytes)
    _g_ledger.set(float(nbytes), entity=entity)


def ledger_remove(entity):
    """Forget ``entity`` and drop its gauge series (e.g. a released
    serving pool)."""
    entity = str(entity)
    with _lock:
        _ledger.pop(entity, None)
    _g_ledger.remove(entity=entity)


def ledger(prefix=None):
    """{entity: bytes}, optionally restricted to entities whose name
    starts with ``prefix``."""
    with _lock:
        if prefix is None:
            return dict(_ledger)
        return {k: v for k, v in _ledger.items()
                if k.startswith(prefix)}


def ledger_total(prefix=None):
    """Sum of ledger bytes, optionally under ``prefix``."""
    return sum(ledger(prefix).values())


def ledger_table(top=None):
    """[(entity, bytes)] sorted descending by bytes; ``top`` limits
    the row count (postmortems and the profiler summary use this)."""
    rows = sorted(ledger().items(), key=lambda kv: -kv[1])
    return rows[:top] if top else rows


# -- runtime poller --------------------------------------------------------

_poller = None                  # (thread, stop_event) when enabled


def _device_label(dev):
    try:
        return f"{dev.platform}:{dev.id}"
    except Exception:
        return str(dev)


def hbm_limit_bytes(device=None):
    """Capacity bytes for ``device`` (any jax device object), or the
    env override, or None when neither side knows. The backend's
    ``memory_stats()['bytes_limit']`` wins when present (TPU/GPU);
    CPU reports None."""
    if device is not None:
        try:
            stats = device.memory_stats()
            if stats and stats.get("bytes_limit"):
                return int(stats["bytes_limit"])
        except Exception:
            pass
    v = os.environ.get(HBM_LIMIT_ENV)
    try:
        return int(float(v)) if v else None
    except ValueError:
        return None


def device_usage():
    """{device label: live-buffer bytes} from ``jax.live_arrays()``
    right now (one sample, no thread). Committed arrays count once per
    device shard; uncommitted single-device arrays count on their
    resident device."""
    import jax
    usage = {}
    for arr in jax.live_arrays():
        try:
            devs = list(arr.devices())
            nbytes = int(arr.nbytes)
        except Exception:
            continue
        if not devs:
            continue
        per_dev = nbytes // max(1, len(devs))
        for d in devs:
            lbl = _device_label(d)
            usage[lbl] = usage.get(lbl, 0) + per_dev
    return usage


def top_live_buffers(k=8):
    """[{'shape', 'dtype', 'nbytes', 'device'}] for the ``k`` largest
    live device buffers — the postmortem's "what is actually resident"
    evidence, and the ledger diff's unattributed-buffer hint."""
    import jax
    rows = []
    for arr in jax.live_arrays():
        try:
            rows.append({
                "shape": tuple(arr.shape),
                "dtype": str(arr.dtype),
                "nbytes": int(arr.nbytes),
                "device": ",".join(sorted(_device_label(d)
                                          for d in arr.devices())),
            })
        except Exception:
            continue
    rows.sort(key=lambda r: -r["nbytes"])
    return rows[:k]


def sample_now():
    """Take one poll sample synchronously: refresh the in-use /
    limit / utilization gauges per device and advance the high-water
    marks. Returns the {device: bytes} usage map. Safe on any backend;
    never raises (telemetry must not fail a step)."""
    try:
        import jax
        usage = device_usage()
        # devices with zero live buffers still get a 0 sample so the
        # series exists and utilization can read as 0, not absent
        for d in jax.local_devices():
            usage.setdefault(_device_label(d), 0)
        limits = {_device_label(d): hbm_limit_bytes(d)
                  for d in jax.local_devices()}
    except Exception:
        return {}
    with _lock:
        for lbl, used in usage.items():
            if used > _high_water.get(lbl, 0):
                _high_water[lbl] = used
    for lbl, used in usage.items():
        _g_in_use.set(float(used), device=lbl)
        _g_hwm.set(float(_high_water.get(lbl, used)), device=lbl)
        limit = limits.get(lbl) or hbm_limit_bytes()
        if limit:
            _g_limit.set(float(limit), device=lbl)
            _g_util.set(used / float(limit), device=lbl)
    return usage


def _poll_loop(stop, interval):
    while not stop.wait(interval):
        sample_now()


def enable(interval=2.0):
    """Start the background live-buffer poller (daemon thread sampling
    every ``interval`` seconds). Idempotent; takes one sample
    immediately so gauges are live before the first tick."""
    global _poller
    with _lock:
        if _poller is not None:
            return
        stop = threading.Event()
        t = threading.Thread(target=_poll_loop,
                             args=(stop, float(interval)),
                             name="memory-poller", daemon=True)
        _poller = (t, stop)
    sample_now()
    t.start()


def disable():
    """Stop the poller and drop the runtime gauge series — disabled
    means ZERO recording (the bench overhead baseline), not stale
    last-values."""
    global _poller
    with _lock:
        p, _poller = _poller, None
    if p is not None:
        p[1].set()
        p[0].join(timeout=5.0)
    _g_in_use.clear()
    _g_util.clear()


def poller_enabled():
    with _lock:
        return _poller is not None


def high_water(device=None):
    """Peak observed in-use bytes — for ``device`` (label) when given,
    else the max across devices. 0 before any sample."""
    with _lock:
        if device is not None:
            return _high_water.get(device, 0)
        return max(_high_water.values(), default=0)


def hbm_utilization_max():
    """Worst-device current utilization in [0, 1] from the last poll
    sample, or None when no limit is known / no sample taken — the
    ShedController's optional HBM-pressure input."""
    vals = list(_g_util.samples().values())
    return max(vals) if vals else None


# -- OOM postmortem --------------------------------------------------------

class OutOfDeviceMemoryError(RuntimeError):
    """A device allocation failed (XLA RESOURCE_EXHAUSTED), re-raised
    with attribution: ``.postmortem`` holds the ledger table, top live
    buffers, the failing boundary, and the compile-time estimate vs
    the limit (docs/DEBUGGING.md 'Why did the job OOM?')."""

    def __init__(self, message, postmortem=None):
        super().__init__(message)
        self.postmortem = postmortem or {}


_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "oom")


def is_oom_error(exc):
    """True when ``exc`` looks like a device out-of-memory failure:
    jaxlib raises XlaRuntimeError whose message leads with
    RESOURCE_EXHAUSTED; allocator paths say 'out of memory'."""
    if exc is None:
        return False
    if isinstance(exc, OutOfDeviceMemoryError):
        return True
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _OOM_MARKERS)


def oom_postmortem(where, exc=None, top_k=8):
    """Build the postmortem dict: everything needed to answer "why did
    it OOM" without archaeology — the ledger's attribution of resident
    bytes, the largest actually-live buffers (shape/dtype/device), the
    latest compiled step's per-segment compile-time estimates, and the
    in-use / limit / high-water numbers per device."""
    try:
        usage = sample_now()
    except Exception:
        usage = {}
    try:
        buffers = top_live_buffers(top_k)
    except Exception:
        buffers = []
    limit = hbm_limit_bytes()
    try:
        import jax
        devs = jax.local_devices()
        if devs:
            limit = hbm_limit_bytes(devs[0]) or limit
    except Exception:
        pass
    return {
        "where": str(where),
        "error": str(exc) if exc is not None else None,
        "ledger": ledger_table(),
        "top_live_buffers": buffers,
        "segments": memory_segments(),
        "peak_bytes_estimate": peak_bytes_per_step(),
        "hbm_bytes_in_use": dict(usage),
        "hbm_bytes_limit": limit,
        "hbm_bytes_high_water": dict(_high_water),
    }


def handle_oom(exc, where, step=None):
    """Convert a RESOURCE_EXHAUSTED into the typed error: build the
    postmortem, bump ``oom_errors_total{where=…}``, trip the
    ``anomaly.trip("oom")`` escalation (health gauge + flight-recorder
    dump embedding the in-flight trace), and raise
    ``OutOfDeviceMemoryError`` chained from the original. Callers
    invoke this only after ``is_oom_error(exc)``."""
    pm = oom_postmortem(where, exc)
    _c_oom.inc(where=str(where))
    try:
        from paddle_tpu.monitor import anomaly
        anomaly.trip("oom", report=pm, step=step)
    except Exception:
        pass
    est = pm.get("peak_bytes_estimate") or 0
    limit = pm.get("hbm_bytes_limit")
    msg = (f"device out of memory at {where}: compile-time peak "
           f"estimate {_fmt_bytes(est)}"
           + (f" vs limit {_fmt_bytes(limit)}" if limit else "")
           + f"; top resident: "
           + ", ".join(f"{e}={_fmt_bytes(b)}"
                       for e, b in pm["ledger"][:3]))
    raise OutOfDeviceMemoryError(msg, postmortem=pm) from exc


# -- admission -------------------------------------------------------------

def admission_headroom(projected_bytes, limit=None):
    """(ok, projected, limit): would adding ``projected_bytes`` on top
    of the current resident high-water mark still fit under ``limit``
    (default: the env/backend HBM limit)? ``ok`` is True when no limit
    is known — admission is advisory without a configured capacity."""
    if limit is None:
        limit = hbm_limit_bytes()
        try:
            import jax
            devs = jax.local_devices()
            if devs:
                limit = hbm_limit_bytes(devs[0]) or limit
        except Exception:
            pass
    resident = max(high_water(), int(ledger_total()))
    projected = int(resident + projected_bytes)
    if not limit:
        return True, projected, None
    return projected <= int(limit), projected, int(limit)


# -- reporting -------------------------------------------------------------

def _fmt_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "?"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.2f}{unit}")
        n /= 1024.0


def summary_line():
    """One human line for ``profiler.summary()``: per-device
    high-water mark (vs limit when known) + the top-3 ledger entries,
    or None when nothing has been recorded."""
    with _lock:
        hwm = dict(_high_water)
    rows = ledger_table(top=3)
    if not hwm and not rows:
        return None
    parts = []
    if hwm:
        limit = hbm_limit_bytes()
        peak = max(hwm.values())
        parts.append("high-water " + _fmt_bytes(peak)
                     + (f"/{_fmt_bytes(limit)}" if limit else "")
                     + f" across {len(hwm)} device(s)")
    if rows:
        parts.append("top: " + ", ".join(
            f"{e}={_fmt_bytes(b)}" for e, b in rows))
    return "memory: " + "; ".join(parts)


def reset():
    """Forget segments, ledger, and high-water marks; stop the poller;
    drop all gauge series (tests)."""
    global _latest_group
    disable()
    with _lock:
        _segments.clear()
        _latest_group = None
        _ledger.clear()
        _high_water.clear()
    _g_temp.clear()
    _g_arg.clear()
    _g_peak.clear()
    _g_ledger.clear()
    _g_in_use.clear()
    _g_limit.clear()
    _g_util.clear()
    _g_hwm.clear()
