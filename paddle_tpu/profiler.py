"""Profiler.

Parity: python/paddle/fluid/profiler.py (start_profiler, stop_profiler,
profiler context manager, reset_profiler) over the reference's two-layer
host+CUPTI tracer (ref: platform/profiler.h, platform/device_tracer.h,
tools/timeline.py). TPU-native: host spans recorded here; device tracing
delegates to jax.profiler (XPlane → TensorBoard/Perfetto), which plays
the CUPTI role.
"""

import contextlib
import json
import os
import threading
import time

import jax

__all__ = [
    "profiler", "start_profiler", "stop_profiler", "reset_profiler",
    "RecordEvent", "record_memory_event", "export_chrome_trace",
    "compilation_cache_stats",
]

_events = []          # (name, start_s, dur_s, tid)
_mem_events = []      # (name, ts_s, bytes, place)
_active = {"on": False, "jax_dir": None}


class RecordEvent:
    """RAII span (ref: platform/profiler.h:81 RecordEvent)."""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if _active["on"]:
            _events.append((self.name, self.t0,
                            time.perf_counter() - self.t0,
                            threading.get_ident()))


def record_memory_event(name, nbytes, place="host"):
    """Memory event (ref: platform/profiler.h:44-57 MemEvent)."""
    if _active["on"]:
        _mem_events.append((name, time.perf_counter(), int(nbytes), place))


def export_chrome_trace(path):
    """Write the recorded host spans + memory counters as a Chrome
    tracing JSON (chrome://tracing / Perfetto) — tools/timeline.py:131
    parity. Device-side traces come from jax.profiler's XPlane dump
    (start_profiler(trace_dir=...)); this export covers the host runtime
    the way the reference's host profiler layer does."""
    events = []
    tids = {}
    for name, t0, dur, tid in _events:
        tids.setdefault(tid, len(tids))
        events.append({
            "name": name, "ph": "X", "cat": "host",
            "ts": t0 * 1e6, "dur": dur * 1e6,
            "pid": 0, "tid": tids[tid],
        })
    for name, ts, nbytes, place in _mem_events:
        events.append({
            "name": f"mem:{place}", "ph": "C", "ts": ts * 1e6,
            "pid": 0, "args": {name: nbytes},
        })
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "paddle_tpu host"}}]
    trace = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    _active["on"] = True
    if trace_dir:
        _active["jax_dir"] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    _active["on"] = False
    if _active["jax_dir"]:
        jax.profiler.stop_trace()
        _active["jax_dir"] = None
    return summary(sorted_key, profile_path)


def reset_profiler():
    _events.clear()
    _mem_events.clear()


def compilation_cache_stats():
    """Persistent XLA compilation-cache counters
    ({'hits','misses','requests'}) — fed by jax's monitoring events via
    core/compile_cache.py. hits > 0 on a restarted worker is the proof
    of a warm restart (the XLA compile came off disk, no recompile)."""
    from paddle_tpu.core import compile_cache
    return compile_cache.stats()


def summary(sorted_key="total", profile_path=None):
    agg = {}
    for name, _, dur, _tid in _events:
        tot, cnt = agg.get(name, (0.0, 0))
        agg[name] = (tot + dur, cnt + 1)
    rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, (tot, cnt) in rows:
        lines.append(f"{name:<40}{cnt:>8}{tot * 1e3:>12.3f}"
                     f"{tot / cnt * 1e3:>12.3f}")
    from paddle_tpu.core import compile_cache
    if compile_cache.is_enabled():
        cc = compile_cache.stats()
        lines.append(f"compilation cache: {cc['hits']} hits / "
                     f"{cc['misses']} misses "
                     f"({compile_cache.cache_dir()})")
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    return report


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             trace_dir=None):
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        print(stop_profiler(sorted_key, profile_path))


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """fluid.profiler.cuda_profiler parity shim: the reference drives
    nvprof; on TPU device tracing is jax.profiler (use profiler()/
    start_profiler with a trace_dir instead). Kept as a working span so
    fluid scripts run unchanged — it records a host span and warns."""
    import warnings
    warnings.warn("cuda_profiler is a no-op on TPU; use "
                  "profiler.profiler(trace_dir=...) for device traces")
    with RecordEvent("cuda_profiler"):
        yield
