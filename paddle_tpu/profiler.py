"""Profiler.

Parity: python/paddle/fluid/profiler.py (start_profiler, stop_profiler,
profiler context manager, reset_profiler) over the reference's two-layer
host+CUPTI tracer (ref: platform/profiler.h, platform/device_tracer.h,
tools/timeline.py). TPU-native: host spans recorded here; device tracing
delegates to jax.profiler (XPlane → TensorBoard/Perfetto), which plays
the CUPTI role.

Event storage is a BOUNDED ring with thread-local shards (the
monitor-registry sharding pattern): appends touch only the calling
thread's deque — no lock, no cross-thread race on a shared list — and a
long run can no longer grow host memory without bound (cap via
``set_max_events``, default 1e6 per thread, env
``PADDLE_TPU_PROFILER_MAX_EVENTS``). When the flight recorder
(monitor/flight_recorder.py) is armed, ``RecordEvent`` also feeds it, so
a postmortem names the span a dying rank was stuck inside.
"""

import collections
import contextlib
import json
import os
import threading
import time

import jax

from paddle_tpu.core.enforce import warn_once
from paddle_tpu.monitor import flight_recorder as _flight
from paddle_tpu.monitor.registry import _ThreadShards

__all__ = [
    "profiler", "start_profiler", "stop_profiler", "reset_profiler",
    "RecordEvent", "record_memory_event", "export_chrome_trace",
    "compilation_cache_stats", "set_max_events",
]

_DEFAULT_MAX_EVENTS = int(os.environ.get(
    "PADDLE_TPU_PROFILER_MAX_EVENTS", str(1_000_000)))


class _ShardedRing:
    """Bounded event store, one deque per writer thread (the shared
    monitor-registry shard idiom: registered under a lock once per
    thread, appended lock-free after; dead threads' deques fold into
    one bounded retired ring so thread churn cannot pin memory). The
    cap is read at every append, so ``set_max_events`` takes effect
    live; it bounds EACH live thread's shard — the reference's profiler
    grows one vector per thread the same way (profiler.cc thread-local
    EventList)."""

    def __init__(self, cap):
        self.cap = int(cap)
        self._retired = collections.deque()
        self._shards = _ThreadShards(collections.deque, self._retire)

    def _retire(self, d):
        self._retired.extend(d)
        self._trim(self._retired)

    def _trim(self, d):
        while len(d) > self.cap:
            try:
                d.popleft()
            except IndexError:
                # a concurrent clear() emptied the deque between the
                # length check and the pop — exactly the state the trim
                # wanted, so done
                break

    def append(self, item):
        d = self._shards.get()
        d.append(item)
        self._trim(d)

    def _all(self):
        return [self._retired] + self._shards.shards()

    def snapshot(self):
        out = []
        for d in self._all():
            out.extend(list(d))
        return out

    def clear(self):
        for d in self._all():
            d.clear()

    def __iter__(self):
        return iter(self.snapshot())

    def __len__(self):
        return sum(len(d) for d in self._all())


_events = _ShardedRing(_DEFAULT_MAX_EVENTS)   # (name, t0, dur, tid, args)
_mem_events = _ShardedRing(_DEFAULT_MAX_EVENTS)  # (name, ts, bytes, place)
_active = {"on": False, "jax_dir": None}


def set_max_events(n):
    """Cap the profiler's per-thread event rings (oldest events drop
    first). Returns the previous cap."""
    prev = _events.cap
    _events.cap = _mem_events.cap = max(int(n), 1)
    return prev


class RecordEvent:
    """RAII span (ref: platform/profiler.h:81 RecordEvent). Feeds the
    profiler ring when profiling is on AND the flight recorder when it
    is armed — a postmortem can name in-flight spans even when the
    profiler was never started. ``args`` rides into the recorded event
    (and the Chrome export); the executor passes ``{"flow": id}`` so
    ``export_chrome_trace`` can pair each dispatch with the fetch that
    materialized it BY ID instead of FIFO order."""

    def __init__(self, name, args=None):
        self.name = name
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter()
        if _flight._enabled:
            _flight.RECORDER.span_push(self.name)
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        if _active["on"]:
            _events.append((self.name, self.t0, dur,
                            threading.get_ident(), self.args))
        if _flight._enabled:
            _flight.RECORDER.span_pop(self.name, dur)


def record_memory_event(name, nbytes, place="host"):
    """Memory event (ref: platform/profiler.h:44-57 MemEvent)."""
    if _active["on"]:
        _mem_events.append((name, time.perf_counter(), int(nbytes),
                            place))


def export_chrome_trace(path):
    """Write the recorded host spans + memory counters as a Chrome
    tracing JSON (chrome://tracing / Perfetto) — tools/timeline.py:131
    parity. Device-side traces come from jax.profiler's XPlane dump
    (start_profiler(trace_dir=...)); this export covers the host runtime
    the way the reference's host profiler layer does.

    Beyond the bare spans: per-tid thread metadata, FLOW arrows linking
    each ``executor.run/dispatch`` slice to the ``executor.run/fetch``
    that materializes it (under async dispatch they are separated in
    time — the arrow shows which fetch paid for which dispatch), and a
    ``steps/s`` counter track derived from consecutive dispatch
    starts.

    Dispatch->fetch pairing is BY SPAN ID: the executor stamps both
    events of one ``run()`` call with the same ``args={"flow": id}``.
    The old per-tid FIFO pairing misattributed whenever a dispatch had
    no fetch — async steps (``return_numpy=False``) emit none, so a
    later blocking step's fetch was paired to the oldest unpaired
    dispatch — and whenever concurrent ``run()`` callers interleaved.
    Events recorded without a flow id (third-party RecordEvents) keep
    the FIFO fallback per tid."""
    spans = sorted(_events.snapshot(), key=lambda e: e[1])
    events = []
    tids = {}
    for name, t0, dur, tid, _args in spans:
        tids.setdefault(tid, len(tids))
        events.append({
            "name": name, "ph": "X", "cat": "host",
            "ts": t0 * 1e6, "dur": dur * 1e6,
            "pid": 0, "tid": tids[tid],
        })
    flow_id = 0
    by_flow = {}                      # executor flow id -> chrome id
    fifo = {}                         # tid -> deque of chrome ids
    prev_dispatch = {}                # tid -> previous dispatch start
    for name, t0, dur, tid, args in spans:
        t = tids[tid]
        if name == "executor.run/dispatch":
            flow_id += 1
            fid = (args or {}).get("flow")
            if fid is not None:
                by_flow[fid] = flow_id
            else:
                fifo.setdefault(t, collections.deque()).append(flow_id)
            events.append({
                "name": "dispatch->fetch", "ph": "s", "cat": "flow",
                "id": flow_id, "ts": (t0 + dur * 0.5) * 1e6,
                "pid": 0, "tid": t,
            })
            last = prev_dispatch.get(t)
            prev_dispatch[t] = t0
            if last is not None and t0 > last:
                events.append({
                    "name": "steps/s", "ph": "C", "ts": t0 * 1e6,
                    "pid": 0, "args": {"steps/s":
                                       round(1.0 / (t0 - last), 3)},
                })
        elif name == "executor.run/fetch":
            fid = (args or {}).get("flow")
            if fid is not None:
                cid = by_flow.pop(fid, None)
            else:
                cid = fifo[t].popleft() if fifo.get(t) else None
            if cid is not None:
                events.append({
                    "name": "dispatch->fetch", "ph": "f", "bp": "e",
                    "cat": "flow", "id": cid,
                    "ts": (t0 + dur * 0.5) * 1e6, "pid": 0, "tid": t,
                })
    for name, ts, nbytes, place in sorted(_mem_events.snapshot(),
                                          key=lambda e: e[1]):
        events.append({
            "name": f"mem:{place}", "ph": "C", "ts": ts * 1e6,
            "pid": 0, "args": {name: nbytes},
        })
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "paddle_tpu host"}}]
    for tid, t in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append({"name": "thread_name", "ph": "M", "pid": 0,
                     "tid": t, "args": {"name": f"host thread {tid}"}})
    trace = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    _active["on"] = True
    if trace_dir:
        _active["jax_dir"] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    _active["on"] = False
    if _active["jax_dir"]:
        jax.profiler.stop_trace()
        _active["jax_dir"] = None
    return summary(sorted_key, profile_path)


def reset_profiler():
    _events.clear()
    _mem_events.clear()


def compilation_cache_stats():
    """Persistent XLA compilation-cache counters
    ({'hits','misses','requests'}) — fed by jax's monitoring events via
    core/compile_cache.py. hits > 0 on a restarted worker is the proof
    of a warm restart (the XLA compile came off disk, no recompile)."""
    from paddle_tpu.core import compile_cache
    return compile_cache.stats()


def summary(sorted_key="total", profile_path=None):
    agg = {}
    for name, _, dur, _tid, _args in _events.snapshot():
        tot, cnt = agg.get(name, (0.0, 0))
        agg[name] = (tot + dur, cnt + 1)
    rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, (tot, cnt) in rows:
        lines.append(f"{name:<40}{cnt:>8}{tot * 1e3:>12.3f}"
                     f"{tot / cnt * 1e3:>12.3f}")
    from paddle_tpu.core import compile_cache
    if compile_cache.is_enabled():
        cc = compile_cache.stats()
        lines.append(f"compilation cache: {cc['hits']} hits / "
                     f"{cc['misses']} misses "
                     f"({compile_cache.cache_dir()})")
    from paddle_tpu.monitor.registry import REGISTRY as _REG
    trips = _REG.get("anomaly_trips_total")
    trip_samples = trips.samples() if trips is not None else {}
    n_trips = sum(trip_samples.values())
    if n_trips:
        kinds = ",".join(sorted(k[0] for k, v in trip_samples.items()
                                if v > 0))
        lines.append(
            f"health: {int(n_trips)} anomaly trip(s) [{kinds}] -- "
            f"postmortems under PADDLE_POSTMORTEM_DIR "
            f"(docs/DEBUGGING.md)")
    from paddle_tpu.monitor import cost as _cost
    mfu = _cost.estimate_mfu()
    if mfu is not None:
        from paddle_tpu.monitor.registry import REGISTRY
        h = REGISTRY.get("executor_step_ms")
        ms = h.sum() / h.count() if h is not None and h.count() else 0.0
        lines.append(
            f"MFU estimate: {mfu * 100:.2f}% "
            f"(flops/step={_cost.flops_per_step():.3e}, "
            f"ms/step={ms:.3f}, peak={_cost.peak_flops():.3e} FLOP/s "
            f"-- see docs/OBSERVABILITY.md for CPU-host caveats)")
    from paddle_tpu.monitor import memory as _memory
    mem_line = _memory.summary_line()
    if mem_line is not None:
        lines.append(
            mem_line + " -- live-buffer accounting; on a CPU host "
            "the limit needs PADDLE_TPU_HBM_LIMIT_BYTES "
            "(docs/OBSERVABILITY.md)")
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    return report


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             trace_dir=None):
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        print(stop_profiler(sorted_key, profile_path))


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """fluid.profiler.cuda_profiler parity shim: the reference drives
    nvprof; on TPU device tracing is jax.profiler (use profiler()/
    start_profiler with a trace_dir instead). Kept as a working span so
    fluid scripts run unchanged — it records a host span and warns ONCE
    per process (a per-epoch shim invocation must not spam the log)."""
    warn_once("cuda_profiler",
              "cuda_profiler is a no-op on TPU; use "
              "profiler.profiler(trace_dir=...) for device traces")
    with RecordEvent("cuda_profiler"):
        yield
