"""fluid.dygraph namespace.

Parity: python/paddle/fluid/dygraph/ (base.py guard/enabled/to_variable,
nn.py layer classes, checkpoint.py save/load_persistables,
learning_rate_scheduler.py decay classes, parallel.py
prepare_context/DataParallel).

Eager execution is JAX's DEFAULT here (SURVEY §2.8: the reference's
tracer/autograd C++ stack collapses into "ops dispatch eagerly, grad()
transforms"), so ``guard()`` simply ensures static-program mode is off
for its scope — the inverse of the reference, where dygraph was the
opt-in mode.
"""

import contextlib

from paddle_tpu.framework import to_variable, no_grad, grad  # noqa: F401
from paddle_tpu.nn.module import Layer                       # noqa: F401
from paddle_tpu.nn import layers as nn                       # noqa: F401
from paddle_tpu.nn.layers import (                           # noqa: F401
    Linear, Conv2D, Conv3D, Conv2DTranspose, Conv3DTranspose, Pool2D, FC,
    BatchNorm, Embedding, GRUUnit, LayerNorm, NCE, PRelu,
    BilinearTensorProduct, GroupNorm, SpectralNorm, TreeConv, RowConv,
)
from paddle_tpu.parallel.env import (                        # noqa: F401
    prepare_context, DataParallel, ParallelEnv,
)
from paddle_tpu.static.program import in_static_mode
from paddle_tpu.layers import learning_rate_scheduler as _sched

__all__ = [
    "enabled", "guard", "to_variable", "no_grad", "grad", "Layer",
    "save_persistables", "load_persistables", "prepare_context",
    "DataParallel",
    "Linear", "Conv2D", "Conv3D", "Pool2D", "FC", "BatchNorm",
    "Embedding", "GRUUnit", "LayerNorm", "NCE", "PRelu",
    "BilinearTensorProduct", "Conv2DTranspose", "Conv3DTranspose",
    "GroupNorm", "SpectralNorm", "TreeConv", "RowConv",
    "NoamDecay", "PiecewiseDecay", "NaturalExpDecay", "ExponentialDecay",
    "InverseTimeDecay", "PolynomialDecay", "CosineDecay",
]


def enabled():
    """dygraph.enabled parity: True when NOT building a static program
    (eager is the default execution model here)."""
    return not in_static_mode()


@contextlib.contextmanager
def guard(place=None):
    """dygraph.guard parity. Eager is the default, so the guard only
    needs to suspend static-program mode for its scope (and restore it
    after) — mirror image of the reference's opt-in tracer."""
    from paddle_tpu.static import program as _prog
    was_static = in_static_mode()
    if was_static:
        _prog.disable_static()
    try:
        yield
    finally:
        if was_static:
            _prog.enable_static()


def save_persistables(model_dict, dirname="save_dir", optimizers=None):
    """dygraph/checkpoint.py save_persistables parity: a Layer's
    state_dict (or a plain param pytree) to ``dirname``."""
    import os
    from paddle_tpu import io as _io
    if hasattr(model_dict, "state_dict"):
        model_dict = model_dict.state_dict()
    os.makedirs(dirname, exist_ok=True)
    _io.save_dygraph(model_dict, os.path.join(dirname, "model"))
    if optimizers is not None:
        _io.save_dygraph(optimizers, os.path.join(dirname, "optimizers"))


def load_persistables(dirname="save_dir"):
    """dygraph/checkpoint.py load_persistables parity: always a
    (param_dict, optimizer_dict_or_None) pair like the reference —
    a shape that depends on directory contents would break callers."""
    import os
    from paddle_tpu import io as _io
    params, _ = _io.load_dygraph(os.path.join(dirname, "model"))
    opt_path = os.path.join(dirname, "optimizers.pdparams")
    opt = None
    if os.path.exists(opt_path):
        opt, _ = _io.load_dygraph(os.path.join(dirname, "optimizers"))
    return params, opt


class LearningRateDecay:
    """dygraph/learning_rate_scheduler.py LearningRateDecay parity: a
    stateful step counter over the functional schedules. Works directly
    as an optimizer ``learning_rate=`` (optimizers call schedules with
    an explicit step), and standalone via step()/__call__()."""

    def __init__(self, schedule, begin=0, step_size=1):
        self._schedule = schedule
        self.step_num = begin
        self.step_size = step_size

    def __call__(self, step=None):
        s = self.step_num if step is None else step
        return self._schedule(s)

    def step(self):
        """Advance the internal counter (the reference advances once
        per optimizer.minimize)."""
        self.step_num += self.step_size
        return self._schedule(self.step_num)


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 learning_rate=1.0):
        super().__init__(_sched.noam_decay(d_model, warmup_steps,
                                           learning_rate), begin, step)


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(_sched.piecewise_decay(boundaries, values),
                         begin, step)


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(_sched.natural_exp_decay(
            learning_rate, decay_steps, decay_rate, staircase),
            begin, step)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(_sched.exponential_decay(
            learning_rate, decay_steps, decay_rate, staircase),
            begin, step)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(_sched.inverse_time_decay(
            learning_rate, decay_steps, decay_rate, staircase),
            begin, step)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(_sched.polynomial_decay(
            learning_rate, decay_steps, end_learning_rate, power, cycle),
            begin, step)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1):
        super().__init__(_sched.cosine_decay(
            learning_rate, step_each_epoch, epochs), begin, step)
