"""Optimizers.

Parity: python/paddle/fluid/optimizer.py (SGD:40, Momentum, DGCMomentum:787,
LarsMomentum, Adagrad, Adam, Adamax, DecayedAdagrad, Adadelta, RMSProp,
Ftrl, Lamb; ModelAverage:2244, ExponentialMovingAverage:2434) and the C++
kernels in operators/optimizers/.

Each optimizer defines a pure per-parameter update rule. Two entry points:

- **functional/eager**: ``state = opt.init(params)`` then
  ``new_params, new_state = opt.apply_gradients(params, grads, state)`` —
  jit-able, used by the eager/module path and by parallel training where
  the whole step is one SPMD computation.
- **static**: ``opt.minimize(loss)`` appends `autodiff` + per-param update
  ops to the Program (the reference's optimizer-op layout), all fused by
  the Executor into the same XLA step.

LR may be a float or a Schedule (layers.learning_rate_scheduler); the
step counter lives in optimizer state, so schedules trace into the
compiled step.
"""

import jax
import jax.numpy as jnp

from paddle_tpu import clip as clip_mod
from paddle_tpu import initializer as I
from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.monitor import tensorwatch as _tensorwatch
from paddle_tpu.static.program import (
    OP_REGISTRY, default_main_program, default_startup_program,
    in_static_mode,
)

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "LarsMomentum", "LarsMomentumOptimizer", "DGCMomentumOptimizer",
    "Adagrad", "AdagradOptimizer", "Adam", "AdamOptimizer", "Adamax",
    "AdamaxOptimizer", "DecayedAdagrad", "DecayedAdagradOptimizer",
    "Adadelta", "AdadeltaOptimizer", "RMSProp", "RMSPropOptimizer", "Ftrl",
    "FtrlOptimizer", "Lamb", "LambOptimizer", "ProximalGD",
    "ProximalGDOptimizer", "ProximalAdagrad", "ProximalAdagradOptimizer",
    "ModelAverage", "ExponentialMovingAverage",
    "PipelineOptimizer",
]


class Optimizer:
    _slot_defaults = {}  # name -> init value
    # update rule touches each param element independently (true for
    # every rule here except Lars/Lamb trust ratios) — required by the
    # kReduce/ZeRO sharded layout in parallel/data_parallel.py
    _elementwise = True

    def __init__(self, learning_rate=0.001, regularization=None,
                 grad_clip=None, name=None):
        self.learning_rate = learning_rate
        self.regularization = regularization
        self.grad_clip = grad_clip
        self.name = name

    # -- rule interface ----------------------------------------------------
    def _slots(self, param):
        return {k: jnp.full(param.shape, v, param.dtype)
                for k, v in self._slot_defaults.items()}

    def _update(self, p, g, slots, lr, t):
        raise NotImplementedError

    def _lr_value(self, step):
        if callable(self.learning_rate):
            return self.learning_rate(step)
        return jnp.asarray(self.learning_rate, jnp.float32)

    # -- functional path ---------------------------------------------------
    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "slots": jax.tree.map(self._slots, params),
        }

    def state_shardings(self, opt_state, pshard, mesh):
        """NamedShardings for opt state: each slot mirrors its param's
        sharding (a slot is elementwise state of its param); the step
        counter is replicated. pshard: param tree of NamedSharding."""
        from jax.sharding import NamedSharding, PartitionSpec
        rep = NamedSharding(mesh, PartitionSpec())
        flat_sh, ptreedef = jax.tree.flatten(pshard)
        flat_slots = ptreedef.flatten_up_to(opt_state["slots"])
        slots_sh = jax.tree.unflatten(
            ptreedef,
            [jax.tree.map(lambda _: sh, sd)
             for sh, sd in zip(flat_sh, flat_slots)])
        return {"step": rep, "slots": slots_sh}

    def apply_gradients(self, params, grads, state, param_meta=None):
        """Returns (new_params, new_state). params/grads are matching
        pytrees; slots is a tree-of-dicts aligned with params."""
        step = state["step"] + 1
        lr = self._lr_value(step.astype(jnp.float32))
        if self.regularization is not None:
            grads = jax.tree.map(self.regularization, params, grads)
        if self.grad_clip is not None:
            grads = self.grad_clip.clip_tree(grads)

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = jax.tree.flatten(grads)[0]
        flat_s = treedef.flatten_up_to(state["slots"]) \
            if self._slot_defaults else [dict() for _ in flat_p]
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            fused = _pallas_fused_update(self, p, g, s, lr, step)
            np_, ns_ = fused if fused is not None \
                else self._update(p, g, s, lr, step)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree.unflatten(treedef, new_p),
                {"step": step, "slots": jax.tree.unflatten(treedef, new_s)})

    # convenience: one-call functional step
    def step(self, params, grads, state=None):
        if state is None:
            state = self.init(params)
        return self.apply_gradients(params, grads, state)

    # -- static path -------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from paddle_tpu.static.backward import append_backward
        if not in_static_mode():
            raise EnforceNotMet(
                "minimize() is the static-graph API; in eager mode use "
                "apply_gradients(params, grads, state)")
        program = loss.block.program
        blk = program.global_block()
        p_g = append_backward(loss, parameter_list, no_grad_set)
        startup = startup_program or default_startup_program()
        sblk = startup.global_block()

        step_name = f"@opt@{self.name or type(self).__name__}@step"
        if not blk.has_var(step_name):
            blk.create_var(name=step_name, shape=(), dtype=jnp.int32,
                           persistable=True)
            sblk.create_var(name=step_name, shape=(), dtype=jnp.int32,
                            persistable=True)
            sblk.append_op(type="init_param", inputs={},
                           outputs={"Out": [step_name]},
                           attrs={"initializer": I.Constant(0),
                                  "shape": (), "dtype": "int32"})
        blk.append_op(type="increment_step", inputs={"X": [step_name]},
                      outputs={"Out": [step_name]}, attrs={})

        # tensor watch (monitor/tensorwatch.py): bracket the update with
        # two in-graph stats ops — pre-clip grad/param global norms
        # before, update-ratio after. Pre-update params thread through
        # as pass-through outputs so ||new - old|| is computable without
        # a host round-trip; the norms reuse clip.global_norm's exact
        # subgraph, so under GradientClipByGlobalNorm XLA CSEs the two.
        watching = _tensorwatch.is_enabled() and p_g
        pre_names = []
        if watching:
            pre_names = [f"@watch@pre@{p.name}" for p, _ in p_g]
            for (p, _g), pn in zip(p_g, pre_names):
                if not blk.has_var(pn):
                    blk.create_var(name=pn, shape=p.shape, dtype=p.dtype)
            if not blk.has_var(_tensorwatch.PRE_VAR):
                blk.create_var(name=_tensorwatch.PRE_VAR, shape=(2,),
                               dtype="float32")
            blk.append_op(
                type="tensor_watch_pre",
                inputs={"Params": [p.name for p, _ in p_g],
                        "Grads": [g.name for _, g in p_g]},
                outputs={"Norms": [_tensorwatch.PRE_VAR],
                         "PreParams": pre_names},
                attrs={})

        clip = self.grad_clip or clip_mod.get_gradient_clip(program)
        if clip is not None:
            gnames = [g.name for _, g in p_g]
            blk.append_op(type="clip_grads", inputs={"X": gnames},
                          outputs={"Out": gnames}, attrs={"clip": clip})

        ops = []
        for p, g in p_g:
            slot_names = []
            for sname, sval in self._slot_defaults.items():
                full = f"{p.name}@{sname}"
                slot_names.append(full)
                if not blk.has_var(full):
                    blk.create_var(name=full, shape=p.shape, dtype=p.dtype,
                                   persistable=True)
                    sblk.create_var(name=full, shape=p.shape, dtype=p.dtype,
                                    persistable=True)
                    sblk.append_op(
                        type="init_param", inputs={},
                        outputs={"Out": [full]},
                        attrs={"initializer": I.Constant(sval),
                               "shape": tuple(int(s) if s not in (None, -1)
                                              else 1 for s in p.shape),
                               "dtype": jnp.dtype(p.dtype).name})
            op = blk.append_op(
                type="apply_optimizer",
                inputs={"Param": [p.name], "Grad": [g.name],
                        "Slots": slot_names, "Step": [step_name]},
                outputs={"ParamOut": [p.name], "SlotOuts": slot_names},
                attrs={"opt": self, "slot_names": list(self._slot_defaults),
                       "regularizer": p.regularizer,
                       "param_lr": p.optimize_attr.get("learning_rate", 1.0)})
            ops.append(op)
        if watching:
            if not blk.has_var(_tensorwatch.STATS_VAR):
                blk.create_var(name=_tensorwatch.STATS_VAR, shape=(4,),
                               dtype="float32")
            blk.append_op(
                type="tensor_watch_post",
                inputs={"Params": [p.name for p, _ in p_g],
                        "PreParams": pre_names,
                        "PreNorms": [_tensorwatch.PRE_VAR]},
                outputs={"Out": [_tensorwatch.STATS_VAR]},
                attrs={})
        return ops, p_g


def _pallas_fused_update(opt, p, g, slots, lr, t):
    """One-VMEM-pass optimizer update via the Pallas kernel registry
    (ops/pallas/optimizer.py) for the three high-traffic rules. Returns
    ``(new_p, new_slots)`` or None when the registry selects the stock
    body / the rule has no fused kernel — the caller then runs
    ``opt._update`` unchanged, so the flag-off path is bit-identical.
    Output dtypes are pinned to the stock rule's promotion behavior via
    ``jax.eval_shape`` over the registered reference body."""
    try:
        from paddle_tpu.ops import pallas as _plk
    except Exception:  # pragma: no cover - partial build
        return None
    cls = type(opt)
    if cls is SGDOptimizer:
        name, args, kw = "fused_sgd", (p, g, lr), {}
        slot_names = ()
    elif cls is MomentumOptimizer or cls is DGCMomentumOptimizer:
        name = "fused_momentum"
        args = (p, g, slots["velocity"], lr)
        kw = {"momentum": opt.momentum, "use_nesterov": opt.use_nesterov}
        slot_names = ("velocity",)
    elif cls is AdamOptimizer:
        name = "fused_adam"
        args = (p, g, slots["moment1"], slots["moment2"], lr, t)
        kw = {"beta1": opt.beta1, "beta2": opt.beta2,
              "epsilon": opt.epsilon}
        slot_names = ("moment1", "moment2")
    else:
        return None
    if not _plk.use_pallas(name) or jnp.size(p) == 0:
        return None
    ref = _plk.get_body(name, "reference")
    want = jax.eval_shape(lambda *a: ref(*a, **kw), *args)
    out = _plk.dispatch(name, *args, **kw)
    if not slot_names:
        return out.astype(want.dtype), slots
    outs = [o.astype(w.dtype) for o, w in zip(out, want)]
    return outs[0], dict(zip(slot_names, outs[1:]))


def _apply_optimizer_compute(ins, attrs):
    opt = attrs["opt"]
    p, g = ins["Param"][0], ins["Grad"][0]
    step = ins["Step"][0]
    slots = dict(zip(attrs["slot_names"], ins.get("Slots", [])))
    reg = attrs.get("regularizer") or opt.regularization
    if reg is not None:
        g = reg(p, g)
    lr = opt._lr_value(step.astype(jnp.float32)) * attrs.get("param_lr", 1.0)
    fused = _pallas_fused_update(opt, p, g, slots, lr, step)
    new_p, new_slots = fused if fused is not None \
        else opt._update(p, g, slots, lr, step)
    return {"ParamOut": [new_p],
            "SlotOuts": [new_slots[k] for k in attrs["slot_names"]]}


OP_REGISTRY["apply_optimizer"] = _apply_optimizer_compute
OP_REGISTRY["increment_step"] = \
    lambda ins, attrs: {"Out": [ins["X"][0] + 1]}


def _clip_grads_compute(ins, attrs):
    clip = attrs["clip"]
    return {"Out": clip.clip_tree(list(ins["X"]))}


OP_REGISTRY["clip_grads"] = _clip_grads_compute
# in-graph tensor-watch stats (computed in monitor/tensorwatch.py,
# appended by minimize() when the watch is enabled)
OP_REGISTRY["tensor_watch_pre"] = _tensorwatch._watch_pre_compute
OP_REGISTRY["tensor_watch_post"] = _tensorwatch._watch_post_compute


# ---------------------------------------------------------------------------
# concrete optimizers (operators/optimizers/*.cc rules)
# ---------------------------------------------------------------------------
class SGDOptimizer(Optimizer):
    """sgd_op.cc"""

    def _update(self, p, g, slots, lr, t):
        return p - lr * g, slots


class MomentumOptimizer(Optimizer):
    """momentum_op.cc (use_nesterov attr supported)."""
    _slot_defaults = {"velocity": 0.0}

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False,
                 **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _update(self, p, g, slots, lr, t):
        v = self.momentum * slots["velocity"] + g
        if self.use_nesterov:
            new_p = p - lr * (g + self.momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class LarsMomentumOptimizer(Optimizer):
    """lars_momentum_op.cc: layer-wise adaptive rate scaling."""
    _slot_defaults = {"velocity": 0.0}
    _elementwise = False     # trust ratio needs whole-param norms

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay

    def _update(self, p, g, slots, lr, t):
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self.lars_coeff * p_norm
            / (g_norm + self.lars_weight_decay * p_norm + 1e-12), 1.0)
        v = self.momentum * slots["velocity"] + lr * local_lr * (
            g + self.lars_weight_decay * p)
        return p - v, {"velocity": v}


class DGCMomentumOptimizer(MomentumOptimizer):
    """DGC (deep gradient compression) momentum (optimizer.py:787).

    On a single computation the top-k sparsification only changes the
    collective payload; the compression transform itself lives in
    parallel/dgc.py and is applied to the gradient tree before allreduce.
    Locally the update rule is momentum-with-correction."""

    def __init__(self, learning_rate, momentum=0.9, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), **kw):
        super().__init__(learning_rate, momentum, **kw)
        self.rampup_begin_step = rampup_begin_step
        self.sparsity = sparsity


class AdagradOptimizer(Optimizer):
    """adagrad_op.cc"""
    _slot_defaults = {"moment": 0.0}

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self._slot_defaults = {"moment": initial_accumulator_value}

    def _update(self, p, g, slots, lr, t):
        m = slots["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self.epsilon), {"moment": m}


class AdamOptimizer(Optimizer):
    """adam_op.cc (bias-corrected)."""
    _slot_defaults = {"moment1": 0.0, "moment2": 0.0}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _update(self, p, g, slots, lr, t):
        t = t.astype(jnp.float32)
        m1 = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        m2 = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g)
        bc = jnp.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        new_p = p - lr * bc * m1 / (jnp.sqrt(m2) + self.epsilon)
        return new_p, {"moment1": m1, "moment2": m2}


class AdamaxOptimizer(Optimizer):
    """adamax_op.cc"""
    _slot_defaults = {"moment": 0.0, "inf_norm": 0.0}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _update(self, p, g, slots, lr, t):
        t = t.astype(jnp.float32)
        m = self.beta1 * slots["moment"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * slots["inf_norm"], jnp.abs(g))
        new_p = p - lr / (1 - self.beta1 ** t) * m / (u + self.epsilon)
        return new_p, {"moment": m, "inf_norm": u}


class DecayedAdagradOptimizer(Optimizer):
    """decayed_adagrad_op.cc"""
    _slot_defaults = {"moment": 0.0}

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def _update(self, p, g, slots, lr, t):
        m = self.decay * slots["moment"] + (1 - self.decay) * jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self.epsilon), {"moment": m}


class AdadeltaOptimizer(Optimizer):
    """adadelta_op.cc"""
    _slot_defaults = {"avg_squared_grad": 0.0, "avg_squared_update": 0.0}

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon, self.rho = epsilon, rho

    def _update(self, p, g, slots, lr, t):
        g2 = self.rho * slots["avg_squared_grad"] + (1 - self.rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots["avg_squared_update"] + self.epsilon) \
            / jnp.sqrt(g2 + self.epsilon)
        u2 = self.rho * slots["avg_squared_update"] + (1 - self.rho) * jnp.square(upd)
        return p - lr * upd, {"avg_squared_grad": g2,
                              "avg_squared_update": u2}


class RMSPropOptimizer(Optimizer):
    """rmsprop_op.cc (centered option)."""
    _slot_defaults = {"mean_square": 0.0, "mean_grad": 0.0, "momentum": 0.0}

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.momentum_coef = momentum
        self.centered = centered

    def _update(self, p, g, slots, lr, t):
        ms = self.rho * slots["mean_square"] + (1 - self.rho) * jnp.square(g)
        mg = self.rho * slots["mean_grad"] + (1 - self.rho) * g \
            if self.centered else slots["mean_grad"]
        denom = ms - jnp.square(mg) if self.centered else ms
        mom = self.momentum_coef * slots["momentum"] \
            + lr * g / jnp.sqrt(denom + self.epsilon)
        return p - mom, {"mean_square": ms, "mean_grad": mg,
                         "momentum": mom}


class FtrlOptimizer(Optimizer):
    """ftrl_op.cc"""
    _slot_defaults = {"squared": 0.0, "linear": 0.0}

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def _update(self, p, g, slots, lr, t):
        sq, lin = slots["squared"], slots["linear"]
        new_sq = sq + jnp.square(g)
        if self.lr_power == -0.5:
            sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
        else:
            sigma = (new_sq ** -self.lr_power - sq ** -self.lr_power) / lr
        new_lin = lin + g - sigma * p
        if self.lr_power == -0.5:
            denom = jnp.sqrt(new_sq) / lr + 2 * self.l2
        else:
            denom = new_sq ** -self.lr_power / lr + 2 * self.l2
        pre = jnp.clip(new_lin, -self.l1, self.l1) - new_lin
        new_p = pre / denom
        return new_p, {"squared": new_sq, "linear": new_lin}


class ProximalGDOptimizer(Optimizer):
    """proximal_gd_op.cc: forward-backward splitting —
    prox_param = p - lr*g; p = sign(prox)*max(|prox| - lr*l1, 0)
    / (1 + lr*l2)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2 = l1, l2

    def _prox(self, prox, lr):
        return (jnp.sign(prox)
                * jnp.maximum(jnp.abs(prox) - lr * self.l1, 0.0)
                / (1.0 + lr * self.l2))

    def _update(self, p, g, slots, lr, t):
        return self._prox(p - lr * g, lr), slots


class ProximalAdagradOptimizer(ProximalGDOptimizer):
    """proximal_adagrad_op.cc: adagrad-scaled proximal step —
    m += g^2; prox = p - lr*g/sqrt(m); then the l1/l2 shrink."""
    _slot_defaults = {"moment": 0.0}

    def _update(self, p, g, slots, lr, t):
        m = slots["moment"] + jnp.square(g)
        prox = p - lr * g / jnp.sqrt(jnp.maximum(m, 1e-12))
        return self._prox(prox, lr), {"moment": m}


class LambOptimizer(Optimizer):
    """lamb_op.cc: layer-adaptive Adam with weight decay."""
    _slot_defaults = {"moment1": 0.0, "moment2": 0.0}
    _elementwise = False     # trust ratio needs whole-param norms

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, **kw)
        self.wd = lamb_weight_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, g, slots, lr, t):
        t = t.astype(jnp.float32)
        m1 = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        m2 = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g)
        m1h = m1 / (1 - self.beta1 ** t)
        m2h = m2 / (1 - self.beta2 ** t)
        r = m1h / (jnp.sqrt(m2h) + self.epsilon) + self.wd * p
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m1, "moment2": m2}


class ModelAverage(Optimizer):
    """optimizer.py:2244 parity: maintain a running average of params for
    eval. Functional form: avg_state = ma.init(params);
    avg_state = ma.accumulate(params, avg_state);
    params_for_eval = ma.average(avg_state)."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self.max_window = max_average_window

    def init(self, params):
        return {"sum": jax.tree.map(jnp.zeros_like, params),
                "count": jnp.zeros((), jnp.int32)}

    def accumulate(self, params, state):
        return {"sum": jax.tree.map(jnp.add, state["sum"], params),
                "count": state["count"] + 1}

    def average(self, state):
        c = jnp.maximum(state["count"], 1).astype(jnp.float32)
        return jax.tree.map(lambda s: s / c, state["sum"])


class ExponentialMovingAverage:
    """optimizer.py:2434 parity (functional)."""

    def __init__(self, decay=0.999, thres_steps=None):
        self.decay = decay

    def init(self, params):
        return {"ema": jax.tree.map(jnp.array, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, params, state):
        step = state["step"] + 1
        d = jnp.minimum(self.decay,
                        (1.0 + step) / (10.0 + step)).astype(jnp.float32)
        ema = jax.tree.map(lambda e, p: d * e + (1 - d) * p,
                           state["ema"], params)
        return {"ema": ema, "step": step}

    def apply(self, state):
        return state["ema"]


# fluid-style short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer


class PipelineOptimizer:
    """fluid.optimizer.PipelineOptimizer parity facade (ref
    optimizer.py:2664: wraps an inner optimizer; PipelineTrainer runs
    program sections over ScopeQueues).

    TPU-native pipelining is the SPMD "pipe" mesh axis —
    parallel.pipeline.PipelineModule(mesh, embed_fn, stage_fn, loss_fn,
    n_micro).make_train_step(inner_opt, schedule="gpipe"|"1f1b") — and
    ``make_train_step`` here delegates straight to it. In the static
    single-program path ``minimize`` applies the inner optimizer over
    the whole (un-cut) program: a one-stage pipeline IS plain training,
    the same collapse the reference performs when cut_list is empty.
    The cut/place/concurrency/queue knobs configure thread pipelines
    over scope queues in the reference; on a TPU mesh their roles are
    played by the pipe-axis size and microbatch count, so they are
    accepted and recorded for inspection only.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0, num_microbatches=None):
        self._inner = optimizer
        self.cut_list = cut_list or []
        self.place_list = place_list or []
        self.concurrency_list = concurrency_list or []
        self.queue_size = queue_size
        self.sync_steps = sync_steps
        self.start_cpu_core_id = start_cpu_core_id
        # only an EXPLICIT num_microbatches is a user contract the mesh
        # path enforces; concurrency_list stays inspection-only
        self._explicit_micro = num_microbatches is not None
        self.num_microbatches = num_microbatches or max(
            len(self.concurrency_list), 1)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self.cut_list:
            import warnings
            warnings.warn(
                "PipelineOptimizer: program cuts run un-pipelined in the "
                "static path; use parallel.pipeline.PipelineModule over a "
                "MeshConfig(pipe=N) mesh for real pipeline parallelism")
        return self._inner.minimize(loss, startup_program,
                                    parameter_list, no_grad_set)

    def make_train_step(self, pipeline_module, schedule="gpipe"):
        """The real (mesh) pipeline path: delegate to PipelineModule.
        The module's own n_micro governs; a conflicting explicit
        num_microbatches here is an error, not a silent no-op."""
        mod_micro = getattr(pipeline_module, "n_micro", None)
        if (self._explicit_micro and mod_micro is not None
                and self.num_microbatches != mod_micro):
            raise ValueError(
                f"PipelineOptimizer(num_microbatches="
                f"{self.num_microbatches}) conflicts with the "
                f"PipelineModule's n_micro={mod_micro}")
        return pipeline_module.make_train_step(self._inner,
                                               schedule=schedule)
