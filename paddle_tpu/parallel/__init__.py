"""Parallelism: mesh, collectives, SPMD data/tensor/pipeline parallel.

TPU-native replacement for the reference's ParallelExecutor + NCCL stack
(ref: framework/parallel_executor.cc, details/all_reduce_op_handle.cc,
platform/nccl_helper.h, operators/collective/): parallelism is expressed
as shardings over a `jax.sharding.Mesh`; XLA inserts ICI/DCN collectives
(ref: SURVEY §2.5/§2.6 translation table).
"""

from paddle_tpu.parallel.mesh import (
    make_mesh, get_mesh, set_mesh, mesh_shape_for, MeshConfig,
)
from paddle_tpu.parallel.spec import ShardingSpec
from paddle_tpu.parallel.collective import (
    all_reduce, all_gather, reduce_scatter, broadcast, ppermute, barrier,
    psum, pmean,
)
from paddle_tpu.parallel.data_parallel import (
    DataParallelTrainer, shard_batch, replicate,
)
from paddle_tpu.parallel.env import (
    DataParallel, ParallelEnv, ParallelStrategy, get_rank,
    get_world_size, prepare_context,
)
from paddle_tpu.parallel.local_sgd import LocalSGDTrainer
from paddle_tpu.parallel import dgc
