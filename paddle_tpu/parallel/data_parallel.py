"""SPMD data-parallel training.

Replaces the reference's whole multi-device stack: ParallelExecutor's
per-device graph cloning + allreduce insertion
(ref: ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:204,454,
details/all_reduce_op_handle.cc:86) becomes ONE jitted computation with
sharding annotations: batch sharded over the "data" axis, params
replicated (or sharded, = the reference's Reduce/ZeRO-ish strategy,
ref: build_strategy.h:57 kReduce). XLA inserts the gradient all-reduce
(bucketed + overlapped — subsuming fused_all_reduce_op_handle.cc).

Gradient accumulation reproduces multi_batch_merge_pass
(ref: ir/multi_batch_merge_pass.cc) as a lax.scan over microbatches.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import DATA_AXIS, get_mesh

__all__ = ["shard_batch", "replicate", "DataParallelTrainer"]


def shard_batch(mesh, batch, axis_name=DATA_AXIS):
    """Place host batch sharded along the data axis (batch dim 0)."""
    def put(x):
        spec = P(axis_name) if jnp.ndim(x) >= 1 else P()
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree.map(put, batch)


def replicate(mesh, tree):
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


class DataParallelTrainer:
    """Compiled SPMD train step.

    loss_fn(params, state, rng, batch) -> (loss, new_state) — pure, as
    produced by nn.Layer.apply. The trainer jits
    (params, opt_state, state, rng, batch) -> (loss, params, opt_state,
    state) with in/out shardings pinned so batch math runs sharded over
    "data" and the grad psum rides ICI.

    accumulate_steps>1 reproduces gradient accumulation (batch-merge):
    the batch's leading dim is split into microbatches scanned
    sequentially before one update.
    """

    def __init__(self, loss_fn, optimizer, mesh=None, axis_name=DATA_AXIS,
                 accumulate_steps=1, param_sharding=None, donate=True):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.mesh = mesh or get_mesh()
        self.axis = axis_name
        self.accum = accumulate_steps
        self.param_sharding = param_sharding  # optional tree of PartitionSpec

        rep = NamedSharding(self.mesh, P())
        data_sh = NamedSharding(self.mesh, P(self.axis))

        def grads_of(params, state, rng, batch):
            def lf(p):
                loss, new_state = self.loss_fn(p, state, rng, batch)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            return loss, grads, new_state

        def step(params, opt_state, state, rng, batch):
            if self.accum == 1:
                loss, grads, new_state = grads_of(params, state, rng, batch)
            else:
                def micro(carry, mb):
                    acc, st, k = carry
                    k, sub = jax.random.split(k)
                    l, g, st = grads_of(params, st, sub, mb)
                    acc = jax.tree.map(jnp.add, acc, g)
                    return (acc, st, k), l

                mbs = jax.tree.map(
                    lambda x: x.reshape((self.accum, -1) + x.shape[1:]),
                    batch)
                zero = jax.tree.map(jnp.zeros_like, params)
                (gsum, new_state, _), losses = jax.lax.scan(
                    micro, (zero, state, rng), mbs)
                grads = jax.tree.map(lambda g: g / self.accum, gsum)
                loss = jnp.mean(losses)
            new_params, new_opt = self.opt.apply_gradients(
                params, grads, opt_state)
            return loss, new_params, new_opt, new_state

        in_sh = (None, None, None, rep, data_sh)
        self._step = jax.jit(
            step,
            in_shardings=in_sh,
            donate_argnums=(0, 1, 2) if donate else (),
        )

    def init(self, init_fn, rng, sample_batch):
        """init_fn(rng, batch) -> (params, state). Params land replicated
        (or per param_sharding) on the mesh — the analog of
        BCastParamsToDevices (ref: parallel_executor.h:81)."""
        params, state = init_fn(rng, sample_batch)
        params = replicate(self.mesh, params)
        state = replicate(self.mesh, state)
        opt_state = self.opt.init(params)
        opt_state = replicate(self.mesh, opt_state)
        return params, opt_state, state

    def step(self, params, opt_state, state, rng, batch):
        batch = shard_batch(self.mesh, batch, self.axis)
        return self._step(params, opt_state, state, rng, batch)
