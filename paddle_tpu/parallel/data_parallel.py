"""SPMD data-parallel training.

Replaces the reference's whole multi-device stack: ParallelExecutor's
per-device graph cloning + allreduce insertion
(ref: ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:204,454,
details/all_reduce_op_handle.cc:86) becomes ONE jitted computation with
sharding annotations: batch sharded over the "data" axis, params
replicated (AllReduce strategy) or sharded over the data axis (the
reference's Reduce strategy, ref: build_strategy.h:38-57 kReduce,
details/reduce_op_handle.cc + broadcast_op_handle.cc — realized here as
a ZeRO layout: params + optimizer state live sharded 1/N per device;
each step all-gathers params for the forward and reduce-scatters
gradients into the local shard's update, via explicit shard_map
collectives so the reduce-scatter/all-gather pair is guaranteed in the
compiled HLO, not left to a partitioner heuristic).

Gradient accumulation reproduces multi_batch_merge_pass
(ref: ir/multi_batch_merge_pass.cc) as a lax.scan over microbatches.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.parallel.mesh import DATA_AXIS, data_axes, get_mesh

from paddle_tpu.parallel._compat import (
    SHARD_MAP_CHECK_KW as _SHARD_MAP_CHECK_KW, shard_map,
)

__all__ = ["shard_batch", "replicate", "zero_param_specs",
           "DataParallelTrainer"]


def shard_batch(mesh, batch, axis_name=DATA_AXIS):
    """Place host batch sharded along the data axis (batch dim 0)."""
    def put(x):
        spec = P(axis_name) if jnp.ndim(x) >= 1 else P()
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree.map(put, batch)


def replicate(mesh, tree):
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


def zero_param_specs(mesh, params, axes=None):
    """ZeRO/kReduce placement policy: for each param leaf, shard its
    LARGEST dimension divisible by the data-axes extent; leaves with no
    such dimension stay replicated. Returns a PartitionSpec tree.

    This is the SPMD expression of ReduceStrategy::kReduce
    (build_strategy.h:57): every device owns 1/N of each parameter and
    its optimizer state instead of the whole thing.
    """
    axes = axes or data_axes(mesh)
    n = int(np.prod([dict(mesh.shape)[a] for a in axes]))

    def spec(x):
        shape = jnp.shape(x)
        best, best_dim = None, -1
        for d, s in enumerate(shape):
            if s % n == 0 and s > best_dim:
                best, best_dim = d, s
        if best is None or n == 1:
            return P()
        entries = [None] * len(shape)
        entries[best] = axes if len(axes) > 1 else axes[0]
        return P(*entries)

    return jax.tree.map(spec, params)


def _sharded_dim(spec):
    """Index of the (single) sharded dimension in a zero spec, or None."""
    for d, e in enumerate(spec):
        if e is not None:
            return d
    return None


class DataParallelTrainer:
    """Compiled SPMD train step.

    loss_fn(params, state, rng, batch) -> (loss, new_state) — pure, as
    produced by nn.Layer.apply. The trainer jits
    (params, opt_state, state, rng, batch) -> (loss, params, opt_state,
    state) with in/out shardings pinned so batch math runs sharded over
    "data" and the grad reduction rides ICI.

    param_sharding selects the reference's ReduceStrategy
    (build_strategy.h:38-57):
      - None            -> kAllReduce: params + opt state replicated,
                           XLA all-reduces gradients.
      - "reduce"/"zero" -> kReduce as ZeRO layout: params + opt state
                           sharded 1/N over the data axis
                           (zero_param_specs). The step all-gathers
                           param shards for the forward and
                           reduce-scatters gradients so each device
                           updates only its own shard — explicit
                           collectives, guaranteed in the HLO.
      - a PartitionSpec tree -> explicit per-param placement; entries
                           may reference the data axis only (model-axis
                           sharding belongs to the megatron specs in
                           models/, not this trainer).

    kReduce requires an ELEMENTWISE optimizer update rule (every rule in
    optimizer.py except Lars/Lamb, whose trust ratios need whole-param
    norms); non-elementwise optimizers raise at construction.

    accumulate_steps>1 reproduces gradient accumulation (batch-merge):
    the batch's leading dim is split into microbatches scanned
    sequentially before one update.
    """

    def __init__(self, loss_fn, optimizer, mesh=None, axis_name=DATA_AXIS,
                 accumulate_steps=1, param_sharding=None, donate=True):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.mesh = mesh or get_mesh()
        self.axis = axis_name
        self.accum = accumulate_steps
        self.param_sharding = param_sharding
        if param_sharding is not None:
            if not getattr(optimizer, "_elementwise", True):
                raise EnforceNotMet(
                    f"param_sharding={param_sharding!r} needs an "
                    f"elementwise optimizer update; "
                    f"{type(optimizer).__name__} computes whole-parameter "
                    f"norms — use the replicated strategy")
            clip = getattr(optimizer, "grad_clip", None)
            if clip is not None and type(clip).__name__ not in (
                    "GradientClipByValue",):
                # norm-based clips would compute per-SHARD norms inside
                # the shard_map body: wrong scale, and device-divergent
                # for replicated leaves
                raise EnforceNotMet(
                    f"param_sharding={param_sharding!r} is incompatible "
                    f"with norm-based gradient clipping "
                    f"({type(clip).__name__}): the norm would be taken "
                    f"over local shards only. Use GradientClipByValue "
                    f"or the replicated strategy")
        # resolved at init() when param shapes are known; read at trace
        # time by the step closure (jit traces on first call, after
        # init), so the shard_map specs bind to the actual placement.
        self._param_specs = None

        rep = NamedSharding(self.mesh, P())
        data_sh = NamedSharding(self.mesh, P(self.axis))

        def grads_of(params, state, rng, batch):
            def lf(p):
                loss, new_state = self.loss_fn(p, state, rng, batch)
                return loss, new_state
            (loss, new_state), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            return loss, grads, new_state

        def fwd_bwd(params, state, rng, batch):
            """(loss, grads, new_state) with optional microbatch scan."""
            if self.accum == 1:
                return grads_of(params, state, rng, batch)

            def micro(carry, mb):
                acc, st, k = carry
                k, sub = jax.random.split(k)
                l, g, st = grads_of(params, st, sub, mb)
                acc = jax.tree.map(jnp.add, acc, g)
                return (acc, st, k), l

            mbs = jax.tree.map(
                lambda x: x.reshape((self.accum, -1) + x.shape[1:]),
                batch)
            zero = jax.tree.map(jnp.zeros_like, params)
            (gsum, new_state, _), losses = jax.lax.scan(
                micro, (zero, state, rng), mbs)
            grads = jax.tree.map(lambda g: g / self.accum, gsum)
            return jnp.mean(losses), grads, new_state

        def plain_step(params, opt_state, state, rng, batch):
            loss, grads, new_state = fwd_bwd(params, state, rng, batch)
            new_params, new_opt = self.opt.apply_gradients(
                params, grads, opt_state)
            return loss, new_params, new_opt, new_state

        def zero_step(params, opt_state, state, rng, batch):
            """kReduce: shard_map over the data axis with explicit
            all-gather (params, broadcast_op_handle.cc's role) and
            reduce-scatter (grads, reduce_op_handle.cc's role)."""
            specs = self._param_specs
            ax = self.axis
            n = dict(self.mesh.shape)[ax]

            def gather(p, spec):
                d = _sharded_dim(spec)
                return p if d is None else lax.all_gather(
                    p, ax, axis=d, tiled=True)

            def scatter(g, spec):
                d = _sharded_dim(spec)
                if d is None:
                    return lax.pmean(g, ax)
                return lax.psum_scatter(
                    g, ax, scatter_dimension=d, tiled=True) / n

            slot_specs = (self._slot_specs(opt_state["slots"])
                          if isinstance(opt_state, dict)
                          and "slots" in opt_state else None)
            opt_specs = jax.tree.map(lambda _: P(), opt_state)
            if slot_specs is not None:
                opt_specs = dict(opt_specs)
                opt_specs["slots"] = slot_specs
            state_specs = jax.tree.map(lambda _: P(), state)
            batch_specs = jax.tree.map(
                lambda x: P(ax) if jnp.ndim(x) >= 1 else P(), batch)

            def body(p_sh, o_sh, st, k, b):
                p_full = jax.tree.map(gather, p_sh, specs)
                loss, g_full, new_st = fwd_bwd(p_full, st, k, b)
                g_sh = jax.tree.map(scatter, g_full, specs)
                loss = lax.pmean(loss, ax)
                new_p, new_o = self.opt.apply_gradients(p_sh, g_sh, o_sh)
                return loss, new_p, new_o, new_st

            kwargs = dict(
                mesh=self.mesh,
                in_specs=(specs, opt_specs, state_specs, P(), batch_specs),
                out_specs=(P(), specs, opt_specs, state_specs),
            )
            kwargs[_SHARD_MAP_CHECK_KW] = False
            return shard_map(body, **kwargs)(
                params, opt_state, state, rng, batch)

        def step(params, opt_state, state, rng, batch):
            if self._param_specs is None:
                return plain_step(params, opt_state, state, rng, batch)
            return zero_step(params, opt_state, state, rng, batch)

        in_sh = (None, None, None, rep, data_sh)
        self._step = jax.jit(
            step,
            in_shardings=in_sh,
            donate_argnums=(0, 1, 2) if donate else (),
        )

    # -- placement ---------------------------------------------------------
    def _resolve_specs(self, params):
        if self.param_sharding is None:
            return None
        if isinstance(self.param_sharding, str):
            if self.param_sharding not in ("reduce", "zero"):
                raise EnforceNotMet(
                    f"param_sharding={self.param_sharding!r}: expected "
                    f"None, 'reduce'/'zero', a PartitionSpec tree, or "
                    f"a parallel.ShardingSpec")
            return zero_param_specs(self.mesh, params, axes=(self.axis,))
        from paddle_tpu.parallel.spec import ShardingSpec
        if isinstance(self.param_sharding, ShardingSpec):
            # the unified spec as placement source: entries must stay
            # on THIS trainer's data axis — the explicit gather/scatter
            # collectives below reduce over self.axis, so a model-axis
            # entry would silently shard without ever being gathered
            specs = self.param_sharding.tree_specs(params)
            for sp in jax.tree.leaves(
                    specs, is_leaf=lambda s: isinstance(s, P)):
                for entry in sp:
                    if entry is not None and entry != self.axis:
                        raise EnforceNotMet(
                            f"DataParallelTrainer(param_sharding=Shard"
                            f"ingSpec): entry {sp} references axis "
                            f"{entry!r}, but this trainer's explicit "
                            f"all-gather/reduce-scatter pair runs over "
                            f"{self.axis!r} only — model-axis "
                            f"placement belongs to the megatron specs "
                            f"or the executor's spec path")
            return specs
        return self.param_sharding

    def _slot_specs(self, slots):
        """Each optimizer slot mirrors its param's spec (slots are
        elementwise state of their param)."""
        flat_specs, ptreedef = jax.tree.flatten(
            self._param_specs,
            is_leaf=lambda x: isinstance(x, P))
        flat_slots = ptreedef.flatten_up_to(slots)
        return jax.tree.unflatten(
            ptreedef,
            [jax.tree.map(lambda _: sp, sd)
             for sp, sd in zip(flat_specs, flat_slots)])

    def param_shardings(self, params):
        """NamedSharding tree for params under the active strategy
        (replicated when param_sharding is None)."""
        specs = self._resolve_specs(params)
        if specs is None:
            return jax.tree.map(
                lambda _: NamedSharding(self.mesh, P()), params)
        return jax.tree.map(
            lambda _, s: NamedSharding(self.mesh, s), params, specs,
            is_leaf=lambda x: isinstance(x, P))

    def init(self, init_fn, rng, sample_batch):
        """init_fn(rng, batch) -> (params, state). Params land replicated
        or sharded per the strategy — the analog of BCastParamsToDevices
        (ref: parallel_executor.h:81) for kAllReduce, and of the
        owner-device param layout of kReduce (reduce_op_handle.cc) for
        "reduce"/"zero"."""
        params, state = init_fn(rng, sample_batch)
        self._param_specs = self._resolve_specs(params)
        pshard = self.param_shardings(params)
        params = jax.tree.map(jax.device_put, params, pshard)
        state = replicate(self.mesh, state)
        opt_state = self.opt.init(params)
        opt_sh = self.opt.state_shardings(opt_state, pshard, self.mesh)
        opt_state = jax.tree.map(jax.device_put, opt_state, opt_sh)
        return params, opt_state, state

    def prepare_sharding(self, params):
        """Resolve + pin the param placement for params NOT produced by
        init() (e.g. restored from a checkpoint): returns the params
        placed per the strategy; also sizes the optimizer-state
        shardings used by subsequent step() traces."""
        self._param_specs = self._resolve_specs(params)
        return jax.tree.map(jax.device_put, params,
                            self.param_shardings(params))

    def step(self, params, opt_state, state, rng, batch):
        if self.param_sharding is not None and self._param_specs is None:
            raise EnforceNotMet(
                "param_sharding was requested but placement is "
                "unresolved — call init(), or prepare_sharding(params) "
                "when restoring from a checkpoint; running now would "
                "silently train fully replicated")
        batch = shard_batch(self.mesh, batch, self.axis)
        return self._step(params, opt_state, state, rng, batch)
