"""GPipe-style microbatch pipeline parallelism over the "pipe" mesh axis.

Reference mechanism: PipelineTrainer + SectionWorker cut a program into
sections, each section a thread pool bound to one device, with scopes
flowing through ScopeQueues between sections (ref: framework/trainer.h:95,
framework/device_worker.h:240, framework/pipeline_trainer.cc,
framework/section_worker.cc; python PipelineOptimizer
ref: python/paddle/fluid/optimizer.py:2664; config
trainer_desc.proto:57-79).

TPU-native redesign: all stages run the SAME jitted SPMD program over a
mesh "pipe" axis. Per-stage parameters are stacked on a leading axis and
sharded over "pipe" (each device holds only its stage's weights). A
lax.scan over M + P - 1 ticks does, per tick: every stage applies its
layer to its current activation, then the activation ring-shifts one
stage forward via lax.ppermute (ICI neighbor hop — the ScopeQueue
equivalent, but double-buffered on-device and overlap-scheduled by XLA).
Microbatch accumulation of gradients replaces the reference's
sync_steps/SyncFunctor cross-pipeline allreduce (device_worker.h:211).

Constraints of the SPMD formulation: every stage's input and output
activation have the same shape (true for stacked transformer blocks /
MLP trunks); ragged stage cuts belong to the embedding/head, which run
outside the pipelined trunk.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.core.flags import define_flag, get_flag
from paddle_tpu.parallel._compat import CHECK_DISABLED as _CHECK_KW
from paddle_tpu.parallel._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, DCN_AXIS, PIPE_AXIS

__all__ = ["stack_stage_params", "stage_param_sharding", "pipeline_apply",
           "PipelineModule", "pipeline_train_1f1b", "gpipe_bubble_fraction",
           "one_f_one_b_bubble_fraction", "schedule_occupancy"]

define_flag(
    "overlap_grad_reduce", False,
    "1F1B schedule: issue the data/dcn_data gradient all-reduce "
    "per-bucket INSIDE the backward scan as each tick produces its "
    "gradient contribution (scan-carried partial reductions XLA can "
    "overlap with the next tick's compute), instead of one fused "
    "reduction after the scan drains. Off by default: bench.py shard "
    "A/Bs it per host — on the CPU harness the per-tick collectives "
    "measured 1.24x SLOWER (synchronous CPU collectives cannot hide "
    "under compute; docs/PERFORMANCE.md records the evidence), so "
    "enable it only where the A/B shows a win (TPU ICI)")


def _data_reduce_axes(mesh, data_axis=DATA_AXIS):
    """The data-parallel mesh axes a pipelined trunk's gradients reduce
    over, DCN-outermost — psum over this tuple is mesh.py's
    hierarchical allreduce (within-slice ICI first, one DCN crossing
    per slice). Axes of extent 1 are dropped: a vacuous collective
    still costs a lowering."""
    shape = dict(mesh.shape)
    return tuple(a for a in (DCN_AXIS, data_axis)
                 if shape.get(a, 1) > 1)


def _data_pspec(axes):
    """P(None, axes) microbatch spec: per-microbatch batch dim (axis 1)
    sharded over the data axes (hierarchically when DCN is present)."""
    if not axes:
        return P()
    return P(None, axes[0] if len(axes) == 1 else tuple(axes))


def stack_stage_params(stage_params):
    """Stack a list of per-stage param pytrees into one tree with a
    leading stage axis (shard it over "pipe")."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def _bind_stage_fn(stage_fn, idx):
    """Per-stage heterogeneity (the section_worker.cc stretch): a
    stage_fn may take (params, x) — homogeneous — or (params, x,
    stage_idx), where stage_idx is this device's traced pipe-axis
    index. A 3-arg fn can lax.switch on the index to run different
    computation per stage (activation shapes must still match across
    stages — the SPMD constraint). Truly device-heterogeneous CPU/TPU
    sections live outside the trunk as the embed/head split."""
    try:
        import inspect
        params = inspect.signature(stage_fn).parameters.values()
        # only REQUIRED positional params count — **kwargs or an
        # optional keyword must not be mistaken for the index slot
        n = sum(1 for p in params
                if p.kind in (p.POSITIONAL_ONLY,
                              p.POSITIONAL_OR_KEYWORD)
                and p.default is p.empty)
    except (TypeError, ValueError):
        n = 2
    if n >= 3:
        return lambda p, x: stage_fn(p, x, idx)
    return stage_fn


def stage_param_sharding(mesh, stacked, pipe_axis=PIPE_AXIS):
    """NamedShardings placing each stage's slice on its pipe-axis device."""
    def sh(x):
        spec = [pipe_axis] + [None] * (np.ndim(x) - 1)
        return NamedSharding(mesh, P(*spec))
    return jax.tree.map(sh, stacked)


def _pipeline_local(stage_fn, stacked_local, mb, n_micro, axis_name):
    """shard_map body. stacked_local: stage params with leading axis of
    local length 1 (this device's stage). mb: [M, ...] microbatched
    activations, replicated. Returns [M, ...] outputs of the LAST stage
    (replicated via final collective)."""
    n_stages = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    stage_fn = _bind_stage_fn(stage_fn, idx)
    my_params = jax.tree.map(lambda x: x[0], stacked_local)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    mb_shape = mb.shape[1:]
    state = jnp.zeros(mb_shape, mb.dtype) + mb[0] * 0.0  # varying-axes seed
    outputs = jnp.zeros((n_micro,) + mb_shape, mb.dtype) + mb * 0.0

    def tick(carry, t):
        state, outputs = carry
        x_in = lax.dynamic_index_in_dim(
            mb, jnp.clip(t, 0, n_micro - 1), keepdims=False)
        x = jnp.where(idx == 0, x_in, state)
        y = stage_fn(my_params, x)
        # last stage banks its result for microbatch (t - (P-1))
        out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        bank = (idx == n_stages - 1) & (t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_slot, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, cur), out_slot, axis=0)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state, outputs),
                               jnp.arange(n_micro + n_stages - 1))
    # outputs live on the last stage; broadcast so every stage returns the
    # same value (out_specs replicated over pipe)
    outputs = lax.psum(
        jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def pipeline_apply(mesh, stage_fn, stacked_params, microbatches,
                   pipe_axis=PIPE_AXIS, data_axis=DATA_AXIS):
    """Run microbatches [M, mb, ...] through the stage pipeline.

    stage_fn(params_of_one_stage, x) -> y with y.shape == x.shape.
    stacked_params: leading stage axis == mesh pipe-axis size.
    The per-microbatch batch dim (axis 1) is sharded over "data" when
    the mesh carries one (DP x PP: each data replica pipelines its own
    slice of every microbatch — mb must divide by the data-axis size).
    Returns [M, mb, ...] final-stage outputs. Differentiable (grads flow
    through ppermute + scan); donate/accumulate at the caller.
    """
    n_micro = int(microbatches.shape[0])
    pspec = jax.tree.map(
        lambda x: P(*([pipe_axis] + [None] * (np.ndim(x) - 1))),
        stacked_params)
    dspec = _data_pspec(_data_reduce_axes(mesh, data_axis))
    body = functools.partial(_pipeline_local, stage_fn, n_micro=n_micro,
                             axis_name=pipe_axis)

    def f(sp, mb):
        return body(sp, mb)

    return shard_map(f, mesh=mesh,
                     in_specs=(pspec, dspec), out_specs=dspec,
                     **_CHECK_KW)(stacked_params, microbatches)


class PipelineModule:
    """PipelineOptimizer-parity convenience (ref: optimizer.py:2664):
    wraps embed -> pipelined trunk -> head + loss into one jitted,
    microbatch-accumulated train step.

    embed_fn(embed_params, batch_x) -> activation
    stage_fn(stage_params, activation) -> activation
    loss_fn(head_params, activation, batch_y) -> scalar mean loss
    """

    def __init__(self, mesh, embed_fn, stage_fn, loss_fn, n_micro,
                 pipe_axis=PIPE_AXIS):
        self.mesh = mesh
        self.embed_fn = embed_fn
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.n_micro = n_micro
        self.pipe_axis = pipe_axis

    def _microbatch(self, x):
        return x.reshape((self.n_micro, x.shape[0] // self.n_micro)
                         + x.shape[1:])

    def sharding_spec(self):
        """The module's placement as the unified ShardingSpec
        (parallel/spec.py): stage params tiled over "pipe" on their
        leading stage axis, embed/head replicated. One annotation
        source for init placement, executor interop, and
        ``checkpoint_axes`` (save(axes=) derivation)."""
        from paddle_tpu.parallel.spec import ShardingSpec
        return ShardingSpec(self.mesh,
                            rules=[("stages/*", P(self.pipe_axis))])

    def loss(self, params, batch_x, batch_y):
        """Full-batch loss: embed -> pipeline trunk -> mean of per-
        microbatch losses (= the reference's microbatch gradient
        accumulation when differentiated)."""
        emb = self.embed_fn(params["embed"], batch_x)
        mb = self._microbatch(emb)
        out = pipeline_apply(self.mesh, self.stage_fn, params["stages"],
                             mb, pipe_axis=self.pipe_axis)
        yb = self._microbatch(batch_y)
        losses = jax.vmap(lambda a, y: self.loss_fn(params["head"], a, y)
                          )(out, yb)
        return jnp.mean(losses)

    def make_train_step(self, optimizer, schedule="gpipe",
                        overlap_grad_reduce=None):
        """schedule='gpipe' differentiates the forward scan (activations
        for all M microbatches live through the backward, plus a
        full-activation output psum); schedule='1f1b' uses the
        interleaved fwd/bwd schedule (bounded residuals, grads stay
        pipe-sharded, no activation broadcast).
        ``overlap_grad_reduce`` (1f1b only; default
        FLAGS_overlap_grad_reduce) issues the data-axes gradient
        all-reduce per bucket inside the backward scan — see
        pipeline_train_1f1b."""
        mesh = self.mesh

        if schedule == "1f1b":
            def loss_and_grads(params, batch_x, batch_y):
                emb, embed_vjp = jax.vjp(
                    lambda ep: self.embed_fn(ep, batch_x),
                    params["embed"])
                mb = self._microbatch(emb)
                yb = self._microbatch(batch_y)

                def out_grad(hp, y, lab):
                    def head_loss(hp, y):
                        return self.loss_fn(hp, y, lab)
                    l, (ghp, gy) = jax.value_and_grad(
                        head_loss, argnums=(0, 1))(hp, y)
                    return l, gy, ghp

                loss, sg, hg, dx = pipeline_train_1f1b(
                    mesh, self.stage_fn, params["stages"], mb,
                    out_grad, yb, head_params=params["head"],
                    pipe_axis=self.pipe_axis,
                    overlap_grad_reduce=overlap_grad_reduce)
                # 1F1B sums per-microbatch grads; the GPipe loss is the
                # MEAN over microbatches — match it
                sg = jax.tree.map(lambda g: g / self.n_micro, sg)
                (g_embed,) = embed_vjp(
                    dx.reshape(emb.shape) / self.n_micro)
                return loss, {"embed": g_embed, "stages": sg,
                              "head": hg}
        elif schedule == "gpipe":
            def loss_and_grads(params, batch_x, batch_y):
                return jax.value_and_grad(self.loss)(
                    params, batch_x, batch_y)
        else:
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}: "
                f"expected 'gpipe' or '1f1b'")

        @jax.jit
        def step(params, opt_state, batch_x, batch_y):
            loss, grads = loss_and_grads(params, batch_x, batch_y)
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state)
            return loss, new_params, new_opt

        def init_fn(params):
            # placement flows from the ONE spec (stages over "pipe",
            # embed/head replicated) — the same object callers hand to
            # the executor or derive save(axes=) from
            pshard = self.sharding_spec().tree_shardings(params)
            params = jax.device_put(params, pshard)
            opt_state = optimizer.init(params)
            opt_state = jax.device_put(
                opt_state, optimizer.state_shardings(opt_state, pshard,
                                                     mesh))
            return params, opt_state

        return init_fn, step


# ---------------------------------------------------------------------------
# 1F1B schedule (VERDICT-r2 next-step #8; ref section_worker.cc runs
# sections concurrently — 1F1B is the TPU-native expression of that
# concurrency with bounded activation memory)
# ---------------------------------------------------------------------------
def gpipe_bubble_fraction(n_micro, n_stages):
    """GPipe bubble: 1 - M/(M+P-1) — all-forward-then-all-backward keeps
    every device idle for P-1 of M+P-1 ticks in each phase."""
    return 1.0 - n_micro / (n_micro + n_stages - 1)


def one_f_one_b_bubble_fraction(n_micro, n_stages):
    """1F1B bubble: forward+backward both run inside one M+2(P-1)-tick
    grid, each device busy 2M of 2(M+2(P-1)) work slots."""
    return 1.0 - n_micro / (n_micro + 2 * (n_stages - 1))


def schedule_occupancy(n_micro, n_stages):
    """Exact tick-grid occupancy of the 1F1B schedule implemented by
    pipeline_train_1f1b: stage s forwards microbatch t-s and backwards
    microbatch t-(2(P-1)-s) at tick t. Returns (busy_slots,
    total_slots, bubble_fraction) counted from the schedule itself (a
    test cross-checks this against the closed form)."""
    M, Pn = n_micro, n_stages
    T = M + 2 * (Pn - 1)
    busy = 0
    for s in range(Pn):
        for t in range(T):
            if 0 <= t - s < M:
                busy += 1                      # forward slot
            if 0 <= t - (2 * (Pn - 1) - s) < M:
                busy += 1                      # backward slot
    total = 2 * T * Pn
    return busy, total, 1.0 - busy / total


def pipeline_train_1f1b(mesh, stage_fn, stacked_params, microbatches,
                        out_grad_fn, labels, head_params=None,
                        pipe_axis=PIPE_AXIS, data_axis=DATA_AXIS,
                        overlap_grad_reduce=None):
    """One fused 1F1B forward+backward pass over the pipelined trunk.

    Unlike pipeline_apply (GPipe: autodiff over the whole forward scan,
    activations for all M microbatches live until the backward), this
    schedules forward and backward per tick: stage s runs fwd of
    microbatch t-s and bwd of microbatch t-(2(P-1)-s) in the same tick,
    holding at most 2P-1 residuals. Activations hop forward and grads
    hop backward via lax.ppermute each tick. There is NO full-activation
    psum epilogue — the trunk emits only the scalar loss, the per-stage
    parameter grads (which STAY sharded over "pipe", exactly where the
    optimizer update needs them), the head grads, and the stage-0 input
    grads for the embed backward.

    stage_fn(stage_params, x) -> y, y.shape == x.shape.
    out_grad_fn(head_params, y_mb, label_mb) ->
    (loss_m, dy_mb, head_grads_m) — the head + loss on one final-stage
    microbatch output (use jax.value_and_grad over the head inside it).
    labels: [M, ...] microbatched targets, delivered per tick (they
    ride the shard_map explicitly — closures over traced arrays are
    not supported). head_params ride replicated (pass {} when the head
    is stateless).
    Returns (mean_loss, stage_grads [stacked, pipe-sharded],
    head_grads, dx [M, ...] input cotangents for the embed backward).

    ``overlap_grad_reduce`` (default: FLAGS_overlap_grad_reduce) moves
    the data/dcn_data gradient all-reduce INSIDE the scan: each tick's
    gradient contribution is pmean'd over the data axes as the backward
    produces it (one collective per parameter bucket per tick,
    scan-carried partial sums), so XLA overlaps the reduction with the
    next tick's fwd/bwd compute instead of serializing one big fused
    reduction after the scan drains. Same math — sum of per-tick means
    == mean of summed grads — so on/off is a pure scheduling A/B
    (bench.py shard measures it; float association differs at the ulp
    level only). Under a hybrid mesh the reduction spans
    ("dcn_data", "data"): hierarchical allreduce, DCN crossed once.
    """
    n_micro = int(microbatches.shape[0])
    n_stages = int(dict(mesh.shape)[pipe_axis])
    resid_len = min(2 * n_stages - 1, n_micro) if n_micro else 1
    ticks = n_micro + 2 * (n_stages - 1)
    if overlap_grad_reduce is None:
        overlap_grad_reduce = bool(get_flag("overlap_grad_reduce"))
    red_axes = _data_reduce_axes(mesh, data_axis)
    shape = dict(mesh.shape)
    n_red = 1
    for a in red_axes:
        n_red *= shape[a]
    overlap = bool(overlap_grad_reduce) and bool(red_axes)

    if head_params is None:
        head_params = {}
    pspec = jax.tree.map(
        lambda x: P(*([pipe_axis] + [None] * (np.ndim(x) - 1))),
        stacked_params)
    dspec = _data_pspec(red_axes)
    hspec = jax.tree.map(lambda _: P(), head_params)
    lspec = dspec

    def body(stacked_local, mb, lb, hp):
        idx = lax.axis_index(pipe_axis)
        fn = _bind_stage_fn(stage_fn, idx)
        params = jax.tree.map(lambda x: x[0], stacked_local)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
        mb_shape = mb.shape[1:]
        zero_act = jnp.zeros(mb_shape, mb.dtype) + mb[0] * 0.0

        # head-grad accumulator mirrors head param structure
        hg_zero = jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p))
            + zero_act.ravel()[0] * 0, hp)
        gp_zero = jax.tree.map(lambda x: jnp.zeros_like(x) + x * 0, params)

        carry0 = dict(
            fwd_in=zero_act,
            bwd_in=zero_act,
            resid=jnp.zeros((resid_len,) + mb_shape, mb.dtype)
            + zero_act * 0.0,
            grad_acc=gp_zero,
            head_acc=hg_zero,
            loss_acc=zero_act.ravel()[0] * 0.0,
            dx_bank=jnp.zeros((n_micro,) + mb_shape, mb.dtype)
            + mb * 0.0,
        )

        def tick(c, t):
            mf = t - idx                               # fwd microbatch
            mbk = t - (2 * (n_stages - 1) - idx)       # bwd microbatch
            fwd_valid = (mf >= 0) & (mf < n_micro)
            bwd_valid = (mbk >= 0) & (mbk < n_micro)

            # ---- forward ----
            x_feed = lax.dynamic_index_in_dim(
                mb, jnp.clip(mf, 0, n_micro - 1), keepdims=False)
            x = jnp.where(idx == 0, x_feed, c["fwd_in"])
            y = fn(params, x)
            resid = lax.dynamic_update_index_in_dim(
                c["resid"], x, jnp.clip(mf, 0, n_micro - 1) % resid_len,
                axis=0)
            resid = jnp.where(fwd_valid, resid, c["resid"])

            # head/loss on the last stage the tick a microbatch finishes
            is_last = idx == n_stages - 1
            lab_m = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(
                    a, jnp.clip(mf, 0, n_micro - 1), keepdims=False),
                lb)
            loss_m, dy_m, hg_m = out_grad_fn(hp, y, lab_m)
            take_head = fwd_valid & is_last
            loss_acc = c["loss_acc"] + jnp.where(take_head, loss_m, 0.0)
            if overlap:
                # per-bucket data-axes reduction as the tick produces
                # the contribution (scan-carried partial mean)
                head_acc = jax.tree.map(
                    lambda a, g: a + lax.pmean(
                        jnp.where(take_head, g, 0.0), red_axes),
                    c["head_acc"], hg_m)
            else:
                head_acc = jax.tree.map(
                    lambda a, g: a + jnp.where(take_head, g, 0.0),
                    c["head_acc"], hg_m)

            # ---- backward (recompute-from-residual vjp) ----
            x_saved = lax.dynamic_index_in_dim(
                c["resid"], jnp.clip(mbk, 0, n_micro - 1) % resid_len,
                keepdims=False)
            g_in = jnp.where(is_last, dy_m, c["bwd_in"])
            # on the last stage fwd and bwd of a microbatch share the
            # tick, so the residual for mbk is this tick's x
            x_for_bwd = jnp.where(is_last, x, x_saved)
            _, vjp_fn = jax.vjp(fn, params, x_for_bwd)
            gp, gx = vjp_fn(g_in)
            if overlap:
                # the gradient all-reduce over data/dcn_data, issued
                # per bucket (per param leaf) the tick the backward
                # produces it — XLA overlaps these with the next
                # tick's compute; the carry accumulates ALREADY-
                # reduced partial sums, so the epilogue reduction
                # disappears
                grad_acc = jax.tree.map(
                    lambda a, g: a + lax.pmean(
                        jnp.where(bwd_valid, g, 0.0), red_axes),
                    c["grad_acc"], gp)
            else:
                grad_acc = jax.tree.map(
                    lambda a, g: a + jnp.where(bwd_valid, g, 0.0),
                    c["grad_acc"], gp)
            dx_bank = lax.dynamic_update_index_in_dim(
                c["dx_bank"],
                jnp.where(bwd_valid & (idx == 0), gx,
                          lax.dynamic_index_in_dim(
                              c["dx_bank"],
                              jnp.clip(mbk, 0, n_micro - 1),
                              keepdims=False)),
                jnp.clip(mbk, 0, n_micro - 1), axis=0)

            # ---- ring hops ----
            fwd_in = lax.ppermute(y, pipe_axis, fwd_perm)
            bwd_in = lax.ppermute(jnp.where(bwd_valid, gx, 0.0 * gx),
                                  pipe_axis, bwd_perm)
            return dict(fwd_in=fwd_in, bwd_in=bwd_in, resid=resid,
                        grad_acc=grad_acc, head_acc=head_acc,
                        loss_acc=loss_acc, dx_bank=dx_bank), None

        c, _ = lax.scan(tick, carry0, jnp.arange(ticks))
        # scalar/param-sized epilogues only — no activation broadcast.
        # Under DP x PP each data replica computed its slice's local
        # mean loss: the global loss is the data-axes mean, and every
        # param grad is likewise the data-axes mean (dx stays sharded
        # over data, scaled by 1/n_red). With overlap on, the grad/head
        # reductions already happened per tick inside the scan.
        grad_acc = c["grad_acc"]
        head_acc = c["head_acc"]
        loss = lax.psum(c["loss_acc"], pipe_axis) / n_micro
        dx_local = c["dx_bank"]
        if red_axes:
            loss = lax.pmean(loss, red_axes)
            if not overlap:
                grad_acc = jax.tree.map(
                    lambda g: lax.pmean(g, red_axes), grad_acc)
                head_acc = jax.tree.map(
                    lambda g: lax.pmean(g, red_axes), head_acc)
            dx_local = dx_local / n_red
        # stage grads stay pipe-local (re-stack the leading axis of
        # length 1 so the output matches stacked_params' pipe sharding)
        stage_grads = jax.tree.map(lambda g: g[None], grad_acc)
        head_grads = jax.tree.map(
            lambda g: lax.psum(g, pipe_axis) / n_micro, head_acc)
        dx = lax.psum(
            jnp.where(idx == 0, dx_local, jnp.zeros_like(dx_local)),
            pipe_axis)
        return loss, stage_grads, head_grads, dx

    return shard_map(
        body, mesh=mesh,
        in_specs=(pspec, dspec,
                  jax.tree.map(lambda _: lspec, labels), hspec),
        out_specs=(P(), pspec, hspec, dspec),
        **_CHECK_KW)(stacked_params, microbatches, labels,
                     head_params)
