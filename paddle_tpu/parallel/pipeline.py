"""GPipe-style microbatch pipeline parallelism over the "pipe" mesh axis.

Reference mechanism: PipelineTrainer + SectionWorker cut a program into
sections, each section a thread pool bound to one device, with scopes
flowing through ScopeQueues between sections (ref: framework/trainer.h:95,
framework/device_worker.h:240, framework/pipeline_trainer.cc,
framework/section_worker.cc; python PipelineOptimizer
ref: python/paddle/fluid/optimizer.py:2664; config
trainer_desc.proto:57-79).

TPU-native redesign: all stages run the SAME jitted SPMD program over a
mesh "pipe" axis. Per-stage parameters are stacked on a leading axis and
sharded over "pipe" (each device holds only its stage's weights). A
lax.scan over M + P - 1 ticks does, per tick: every stage applies its
layer to its current activation, then the activation ring-shifts one
stage forward via lax.ppermute (ICI neighbor hop — the ScopeQueue
equivalent, but double-buffered on-device and overlap-scheduled by XLA).
Microbatch accumulation of gradients replaces the reference's
sync_steps/SyncFunctor cross-pipeline allreduce (device_worker.h:211).

Constraints of the SPMD formulation: every stage's input and output
activation have the same shape (true for stacked transformer blocks /
MLP trunks); ragged stage cuts belong to the embedding/head, which run
outside the pipelined trunk.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, PIPE_AXIS

__all__ = ["stack_stage_params", "stage_param_sharding", "pipeline_apply",
           "PipelineModule"]


def stack_stage_params(stage_params):
    """Stack a list of per-stage param pytrees into one tree with a
    leading stage axis (shard it over "pipe")."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_param_sharding(mesh, stacked, pipe_axis=PIPE_AXIS):
    """NamedShardings placing each stage's slice on its pipe-axis device."""
    def sh(x):
        spec = [pipe_axis] + [None] * (np.ndim(x) - 1)
        return NamedSharding(mesh, P(*spec))
    return jax.tree.map(sh, stacked)


def _pipeline_local(stage_fn, stacked_local, mb, n_micro, axis_name):
    """shard_map body. stacked_local: stage params with leading axis of
    local length 1 (this device's stage). mb: [M, ...] microbatched
    activations, replicated. Returns [M, ...] outputs of the LAST stage
    (replicated via final collective)."""
    n_stages = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    my_params = jax.tree.map(lambda x: x[0], stacked_local)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    mb_shape = mb.shape[1:]
    state = jnp.zeros(mb_shape, mb.dtype) + mb[0] * 0.0  # varying-axes seed
    outputs = jnp.zeros((n_micro,) + mb_shape, mb.dtype) + mb * 0.0

    def tick(carry, t):
        state, outputs = carry
        x_in = lax.dynamic_index_in_dim(
            mb, jnp.clip(t, 0, n_micro - 1), keepdims=False)
        x = jnp.where(idx == 0, x_in, state)
        y = stage_fn(my_params, x)
        # last stage banks its result for microbatch (t - (P-1))
        out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        bank = (idx == n_stages - 1) & (t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_slot, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, cur), out_slot, axis=0)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state, outputs),
                               jnp.arange(n_micro + n_stages - 1))
    # outputs live on the last stage; broadcast so every stage returns the
    # same value (out_specs replicated over pipe)
    outputs = lax.psum(
        jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def pipeline_apply(mesh, stage_fn, stacked_params, microbatches,
                   pipe_axis=PIPE_AXIS, data_axis=DATA_AXIS):
    """Run microbatches [M, mb, ...] through the stage pipeline.

    stage_fn(params_of_one_stage, x) -> y with y.shape == x.shape.
    stacked_params: leading stage axis == mesh pipe-axis size.
    The per-microbatch batch dim (axis 1) is sharded over "data" when
    the mesh carries one (DP x PP: each data replica pipelines its own
    slice of every microbatch — mb must divide by the data-axis size).
    Returns [M, mb, ...] final-stage outputs. Differentiable (grads flow
    through ppermute + scan); donate/accumulate at the caller.
    """
    n_micro = int(microbatches.shape[0])
    pspec = jax.tree.map(
        lambda x: P(*([pipe_axis] + [None] * (np.ndim(x) - 1))),
        stacked_params)
    dspec = P(None, data_axis) if mesh.shape.get(data_axis, 1) > 1 else P()
    body = functools.partial(_pipeline_local, stage_fn, n_micro=n_micro,
                             axis_name=pipe_axis)

    def f(sp, mb):
        return body(sp, mb)

    return shard_map(f, mesh=mesh,
                     in_specs=(pspec, dspec), out_specs=dspec,
                     check_vma=False)(stacked_params, microbatches)


class PipelineModule:
    """PipelineOptimizer-parity convenience (ref: optimizer.py:2664):
    wraps embed -> pipelined trunk -> head + loss into one jitted,
    microbatch-accumulated train step.

    embed_fn(embed_params, batch_x) -> activation
    stage_fn(stage_params, activation) -> activation
    loss_fn(head_params, activation, batch_y) -> scalar mean loss
    """

    def __init__(self, mesh, embed_fn, stage_fn, loss_fn, n_micro,
                 pipe_axis=PIPE_AXIS):
        self.mesh = mesh
        self.embed_fn = embed_fn
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.n_micro = n_micro
        self.pipe_axis = pipe_axis

    def _microbatch(self, x):
        return x.reshape((self.n_micro, x.shape[0] // self.n_micro)
                         + x.shape[1:])

    def loss(self, params, batch_x, batch_y):
        """Full-batch loss: embed -> pipeline trunk -> mean of per-
        microbatch losses (= the reference's microbatch gradient
        accumulation when differentiated)."""
        emb = self.embed_fn(params["embed"], batch_x)
        mb = self._microbatch(emb)
        out = pipeline_apply(self.mesh, self.stage_fn, params["stages"],
                             mb, pipe_axis=self.pipe_axis)
        yb = self._microbatch(batch_y)
        losses = jax.vmap(lambda a, y: self.loss_fn(params["head"], a, y)
                          )(out, yb)
        return jnp.mean(losses)

    def make_train_step(self, optimizer):
        mesh = self.mesh

        @jax.jit
        def step(params, opt_state, batch_x, batch_y):
            loss, grads = jax.value_and_grad(self.loss)(
                params, batch_x, batch_y)
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state)
            return loss, new_params, new_opt

        def init_fn(params):
            stacked_sh = stage_param_sharding(mesh, params["stages"],
                                              self.pipe_axis)
            params = dict(params)
            params["stages"] = jax.device_put(params["stages"], stacked_sh)
            opt_state = optimizer.init(params)
            pshard = {
                "embed": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), params["embed"]),
                "stages": stacked_sh,
                "head": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), params["head"]),
            }
            opt_state = jax.device_put(
                opt_state, optimizer.state_shardings(opt_state, pshard,
                                                     mesh))
            return params, opt_state

        return init_fn, step
