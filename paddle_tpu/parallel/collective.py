"""Collective ops.

Parity: operators/collective/ (c_allreduce_{sum,max,min,prod}
ref: collective/c_allreduce_op.h:33, c_allgather, c_reducescatter,
c_broadcast, c_sync_*) and the python mirrors (layers/collective.py).

TPU-native: these are jax.lax collectives over named mesh axes, usable
inside shard_map/pjit — XLA schedules them on ICI and overlaps with
compute (the reference needed dedicated comm streams + sync ops for
that; c_sync_calc_stream/c_sync_comm_stream have no analog because the
compiler owns scheduling). ring_id → axis_name.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.parallel.mesh import DATA_AXIS

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "ppermute",
    "barrier", "psum", "pmean", "pmax", "pmin", "axis_index",
    "bucketed_all_reduce",
]


def psum(x, axis_name=DATA_AXIS):
    return lax.psum(x, axis_name)


def pmean(x, axis_name=DATA_AXIS):
    return lax.pmean(x, axis_name)


def pmax(x, axis_name=DATA_AXIS):
    return lax.pmax(x, axis_name)


def pmin(x, axis_name=DATA_AXIS):
    return lax.pmin(x, axis_name)


def all_reduce(x, op="sum", ring_id=None, axis_name=DATA_AXIS):
    """c_allreduce parity; op in sum/max/min/prod/avg."""
    axis = ring_id if isinstance(ring_id, str) else axis_name
    if op == "sum":
        return lax.psum(x, axis)
    if op == "avg" or op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "prod":
        # Exact product (c_allreduce_prod, collective/c_allreduce_op.h:33):
        # all-gather the shards and reduce locally. An exp(psum(log))
        # formulation is NaN for negatives and loses precision; the gather
        # costs N× transient memory but matches the reference bit-for-bit
        # semantics (zeros, negatives, infs all behave like jnp.prod).
        return jnp.prod(lax.all_gather(x, axis, axis=0, tiled=False),
                        axis=0)
    raise ValueError(f"unknown allreduce op {op}")


def all_gather(x, axis_name=DATA_AXIS, axis=0, tiled=True):
    """c_allgather parity: concatenate shards along `axis`."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name=DATA_AXIS, axis=0):
    """c_reducescatter parity."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                            tiled=True)


def broadcast(x, root=0, axis_name=DATA_AXIS):
    """c_broadcast parity: every participant gets root's value."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, perm, axis_name=DATA_AXIS):
    """collective_permute — the ring-attention / pipeline transfer
    primitive."""
    return lax.ppermute(x, axis_name, perm)


def barrier(axis_name=DATA_AXIS):
    """No-op under SPMD (XLA programs are globally scheduled); kept for
    API parity with the reference's barrier ops."""
    return None


def axis_index(axis_name=DATA_AXIS):
    return lax.axis_index(axis_name)


def bucketed_all_reduce(tree, axis_name=DATA_AXIS, bucket_mb=32.0,
                        op="sum"):
    """Fused/bucketed gradient all-reduce with the reference's
    bucket-size knob: coalesce the tree's leaves into ~bucket_mb
    buckets (alloc_continuous_space_for_grad_pass.cc role), one
    collective per bucket (fused_all_reduce_op_handle.cc;
    knob parity: BuildStrategy fuse_all_reduce_ops +
    DistributedStrategy.fuse_grad_size_in_MB). ``axis_name`` may be a
    tuple — e.g. ("dcn_data", "data") for the hierarchical DCN+ICI
    reduction (mesh.data_axes). Usable inside shard_map; under plain
    pjit sharding annotations XLA buckets automatically and this is
    unnecessary."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    cap = max(int(bucket_mb * (1 << 20)), 1)
    # buckets are PER DTYPE: casting everything through f32 would
    # double bf16/f16 wire bytes and truncate f64
    buckets, cur, cur_bytes, cur_dt = [], [], 0, None
    order = sorted(range(len(leaves)),
                   key=lambda i: str(jnp.asarray(leaves[i]).dtype))
    for i in order:
        leaf = jnp.asarray(leaves[i])
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and (cur_bytes + nbytes > cap or leaf.dtype != cur_dt):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dt = leaf.dtype
    if cur:
        buckets.append(cur)
    out = [None] * len(leaves)
    for idxs in buckets:
        flat = jnp.concatenate(
            [jnp.asarray(leaves[i]).ravel() for i in idxs])
        red = all_reduce(flat, op=op, axis_name=axis_name)
        off = 0
        for i in idxs:
            n = jnp.asarray(leaves[i]).size   # leaves may be scalars
            out[i] = red[off:off + n].reshape(jnp.shape(leaves[i]))
            off += n
    return jax.tree.unflatten(treedef, out)
