"""Unified mesh partitioner: ONE sharding spec from program to pjit.

The reference unified tensor/pipeline/data parallelism under a single
execution stack (CompiledProgram + ParallelExecutor + the multi-device
graph passes); here the same unification is a ``ShardingSpec`` —
program-level sharding annotations over the canonical named axes of
``parallel/mesh.py`` (data/model/pipe/seq/expert/dcn_data) that every
layer consumes:

- ``Executor.prepare``/``run`` (static path): a
  ``CompiledProgram.with_mesh_sharding(spec)`` program places its
  persistable state per ``param_spec``, shards feed batches per
  ``feed_spec``, and pins the spec'd names inside each compiled device
  segment with ``with_sharding_constraint`` — the pjit lowering (the
  jax 0.4.37 pin has no ``jax.shard_map``; see parallel/_compat.py).
- the functional trainers (pipeline/data_parallel/models): pytrees map
  through the same spec by tree path (``tree_specs``/``tree_shardings``).
- the checkpoint layer: ``checkpoint_axes`` derives ``save(axes=)``
  annotations for PR 6's reshard planner from the very same spec.

Specs are name-keyed. ``params`` holds exact names; ``rules`` holds
``(fnmatch pattern, PartitionSpec)`` pairs tried in order — the
program-level analog of the reference's per-param attribute
annotations. A name matching neither is replicated. Feed arrays
default to batch-dim sharding over the mesh's data axes
(``dcn_data``+``data`` when hybrid), the hierarchical-allreduce
placement of mesh.py.
"""

import fnmatch

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.parallel.mesh import data_axes, get_mesh

__all__ = ["ShardingSpec"]


def _as_pspec(entry):
    if isinstance(entry, P):
        return entry
    if entry is None:
        return P()
    if isinstance(entry, (tuple, list)):
        return P(*entry)
    if isinstance(entry, str):
        return P(entry)
    raise EnforceNotMet(
        f"sharding entry must be a PartitionSpec / axis name / tuple / "
        f"None, got {type(entry).__name__}")


def _entry_axes(entry):
    """The mesh axis names one PartitionSpec DIMENSION entry references."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def _leaf_path(path):
    """jax key-path -> "a/b/0" string the rules match against."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # pragma: no cover - exotic key types
            parts.append(str(k))
    return "/".join(parts)


class ShardingSpec:
    """Program-level sharding annotations over a named-axis mesh.

    ``params``: {exact name: PartitionSpec} — per-param placement.
    ``rules``: [(fnmatch pattern, PartitionSpec)] tried in order, after
    exact names; patterns match static var names ("w_qkv_3") and
    functional tree paths ("stages/w"). Unmatched names are replicated.
    ``feeds``: {feed name: PartitionSpec} overriding the default
    batch-dim-0 sharding over ``feed_batch_axes`` (default: the mesh's
    data axes, DCN-outermost — scalars stay replicated).
    """

    def __init__(self, mesh=None, params=None, rules=None, feeds=None,
                 feed_batch_axes=None):
        self.mesh = mesh if mesh is not None else get_mesh()
        self.params = {n: _as_pspec(s) for n, s in (params or {}).items()}
        self.rules = [(pat, _as_pspec(s)) for pat, s in (rules or [])]
        self.feeds = {n: _as_pspec(s) for n, s in (feeds or {}).items()}
        if feed_batch_axes is None:
            self.feed_batch_axes = data_axes(self.mesh)
        else:
            self.feed_batch_axes = tuple(feed_batch_axes)
        shape = dict(self.mesh.shape)
        for axes_src in ([("feed_batch_axes", P(self.feed_batch_axes))]
                         + [(f"params[{n!r}]", s)
                            for n, s in self.params.items()]
                         + [(f"rules[{pat!r}]", s)
                            for pat, s in self.rules]
                         + [(f"feeds[{n!r}]", s)
                            for n, s in self.feeds.items()]):
            where, sp = axes_src
            seen = []
            for entry in sp:
                for a in _entry_axes(entry):
                    if a not in shape:
                        raise EnforceNotMet(
                            f"ShardingSpec {where} references mesh axis "
                            f"{a!r}, but the mesh only has axes "
                            f"{tuple(shape)}")
                    if a in seen:
                        raise EnforceNotMet(
                            f"ShardingSpec {where} uses mesh axis {a!r} "
                            f"on more than one dimension")
                    seen.append(a)

    @classmethod
    def from_tree(cls, mesh, spec_tree, **kw):
        """Build a ShardingSpec from an existing PartitionSpec PYTREE
        (the currency of the functional models, e.g.
        ``models.transformer.param_specs``): every leaf becomes an
        exact path-keyed entry, so ``tree_specs`` round-trips it and
        ``checkpoint_axes``/executor interop come for free."""
        flat, _ = jax.tree_util.tree_flatten_with_path(
            spec_tree, is_leaf=lambda s: isinstance(s, P))
        return cls(mesh,
                   params={_leaf_path(p): s for p, s in flat}, **kw)

    # -- lookups -----------------------------------------------------------
    def _lookup(self, name):
        """Explicit entry for ``name`` (exact, then first matching
        rule), or None when the spec says nothing about it."""
        sp = self.params.get(name)
        if sp is not None:
            return sp
        for pat, sp in self.rules:
            if fnmatch.fnmatchcase(name, pat):
                return sp
        return None

    def param_spec(self, name):
        """PartitionSpec for a param/state name (replicated default)."""
        sp = self._lookup(name)
        return sp if sp is not None else P()

    def feed_spec(self, name, ndim):
        """PartitionSpec for a feed: explicit entry, else batch dim 0
        over the data axes (scalars replicated)."""
        sp = self.feeds.get(name)
        if sp is not None:
            return sp
        if ndim == 0 or not self.feed_batch_axes:
            return P()
        axes = (self.feed_batch_axes[0]
                if len(self.feed_batch_axes) == 1
                else tuple(self.feed_batch_axes))
        return P(axes)

    def axis_extent(self, entry):
        """Product of mesh extents one dimension entry shards over."""
        shape = dict(self.mesh.shape)
        n = 1
        for a in _entry_axes(entry):
            n *= shape[a]
        return n

    # -- shardings ---------------------------------------------------------
    def param_sharding(self, name):
        return NamedSharding(self.mesh, self.param_spec(name))

    def feed_sharding(self, name, ndim):
        return NamedSharding(self.mesh, self.feed_spec(name, ndim))

    def state_shardings(self, names):
        """{name: NamedSharding} for the executor's persistable state."""
        return {n: self.param_sharding(n) for n in names}

    def constraint_for(self, name):
        """NamedSharding to pin ``name`` to inside a compiled segment,
        or None when the spec has nothing explicit for it (replicated-
        by-default names are left to the partitioner). Gradient names
        (``<param>@GRAD``) inherit their param's placement — the
        gradient collective then reduces shard-local buffers instead of
        gathered replicas."""
        base = name[:-len("@GRAD")] if name.endswith("@GRAD") else name
        sp = self._lookup(base)
        return None if sp is None else NamedSharding(self.mesh, sp)

    def validate_leaf(self, name, shape, sp=None):
        """Divisibility check: every sharded dim of ``shape`` must
        divide by the extent of the axes tiling it."""
        sp = self.param_spec(name) if sp is None else sp
        for d, entry in enumerate(sp):
            if entry is None:
                continue
            if d >= len(shape):
                raise EnforceNotMet(
                    f"ShardingSpec for {name!r} shards dim {d} but the "
                    f"value has shape {tuple(shape)}")
            n = self.axis_extent(entry)
            if n > 1 and shape[d] % n != 0:
                raise EnforceNotMet(
                    f"ShardingSpec for {name!r}: dim {d} of shape "
                    f"{tuple(shape)} is not divisible by the "
                    f"{n}-way {_entry_axes(entry)} tiling")
        return sp

    # -- placement ---------------------------------------------------------
    def shard_feeds(self, feeds):
        """device_put a {name: array} feed dict per ``feed_spec``.
        Raises on a batch dim that does not divide the data axes — the
        same contract as data-parallel batch sharding. An array already
        carrying its target sharding passes through untouched — the
        device-side double-buffer path (``Executor.feed_stage`` staging
        batch N+1 in the prefetch worker) relies on this to keep the
        H2D hop off the step's critical path."""
        out = {}
        for k, v in feeds.items():
            def put(x, k=k):
                sp = self.feed_spec(k, np.ndim(x))
                shape = np.shape(x)
                for d, entry in enumerate(sp):
                    if entry is None:
                        continue
                    if d >= len(shape):
                        raise EnforceNotMet(
                            f"ShardingSpec feed entry for {k!r} shards "
                            f"dim {d} but the fed array has shape "
                            f"{tuple(shape)}")
                    n = self.axis_extent(entry)
                    if n > 1 and shape[d] % n != 0:
                        raise EnforceNotMet(
                            f"feed {k!r} batch dim {d} ({shape[d]}) "
                            f"is not divisible by the {n}-device "
                            f"{_entry_axes(entry)} mesh axes")
                target = NamedSharding(self.mesh, sp)
                s = getattr(x, "sharding", None)
                if s is not None:
                    try:
                        if s == target or s.is_equivalent_to(
                                target, np.ndim(x)):
                            return x
                    except Exception:
                        pass
                return jax.device_put(x, target)
            out[k] = jax.tree.map(put, v)
        return out

    def place_state(self, state):
        """device_put a flat {name: value} state dict per the spec."""
        out = {}
        for n, v in state.items():
            sh = self.param_sharding(n)

            def put(x, n=n, sh=sh):
                self.validate_leaf(n, np.shape(x))
                return jax.device_put(x, sh)
            out[n] = jax.tree.map(put, v)
        return out

    # -- pytree (functional-path) currency ---------------------------------
    def tree_specs(self, tree):
        """PartitionSpec pytree for a params pytree: each leaf is looked
        up by its "a/b/0" tree path through the same exact-name + rule
        table (the functional trainers' currency)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        return jax.tree_util.tree_unflatten(
            treedef, [self.param_spec(_leaf_path(p)) for p, _ in flat])

    def tree_shardings(self, tree):
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self.tree_specs(tree),
                            is_leaf=lambda s: isinstance(s, P))

    def place_tree(self, tree):
        """device_put a params pytree per the spec (divisibility-
        checked)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        placed = []
        for p, x in flat:
            name = _leaf_path(p)
            sp = self.validate_leaf(name, np.shape(x))
            placed.append(jax.device_put(
                x, NamedSharding(self.mesh, sp)))
        return jax.tree_util.tree_unflatten(treedef, placed)

    # -- checkpoint interop (PR 6 reshard planner) -------------------------
    def checkpoint_axes(self, state):
        """Derive ``CheckpointManager.save(axes=)`` annotations from
        this spec: a pytree congruent to ``state`` with, per leaf, the
        dimension index it is sharded on (single named axis) or None
        (replicated / trivially tiled by size-1 axes).

        Multi-axis tilings — one dim over an axis TUPLE, or two sharded
        dims — raise ``CheckpointTopologyError``: the re-slice planner
        covers single-named-axis tilings only, and a wrong annotation
        would make an elastic restore silently concatenate shards along
        the wrong dim.
        """
        from paddle_tpu.io_checkpoint import CheckpointTopologyError
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        axes = []
        for p, x in flat:
            name = _leaf_path(p)
            sp = self.param_spec(name)
            sharded = [(d, entry) for d, entry in enumerate(sp)
                       if entry is not None
                       and self.axis_extent(entry) > 1]
            if not sharded:
                axes.append(None)
                continue
            if len(sharded) > 1:
                raise CheckpointTopologyError(
                    f"cannot derive save(axes=) for {name!r}: spec "
                    f"{sp} tiles {len(sharded)} dimensions — the "
                    f"reshard planner covers single-named-axis params "
                    f"only")
            d, entry = sharded[0]
            names = _entry_axes(entry)
            if len(names) > 1:
                raise CheckpointTopologyError(
                    f"cannot derive save(axes=) for {name!r}: spec "
                    f"{sp} tiles dim {d} over the axis tuple {names} — "
                    f"the reshard planner covers single-named-axis "
                    f"params only")
            axes.append(d)
        return jax.tree_util.tree_unflatten(treedef, axes)

    def __repr__(self):
        return (f"ShardingSpec(mesh={dict(self.mesh.shape)}, "
                f"params={len(self.params)}, rules={len(self.rules)}, "
                f"feeds={len(self.feeds)}, "
                f"feed_batch_axes={self.feed_batch_axes})")
