"""Expert-parallel Mixture-of-Experts FFN (GShard/Switch-style).

Beyond the reference (the 2019 codebase has no MoE — SURVEY §2.5 lists
EP alongside TP/SP as TPU-build stretch): a top-k gated expert FFN
whose experts shard over the mesh's "expert" axis
(MeshConfig(expert=N)). Routing uses the dense-dispatch formulation —
one-hot dispatch/combine einsums over a capacity-bucketed layout — so
under pjit/GSPMD the token exchange lowers to all_to_all collectives
on ICI, the TPU-native shape of expert parallelism; there is no
host-side router.

Semantics (Switch/GShard defaults): softmax gate over experts, top-k
(k=1 or 2) selection, per-expert capacity
C = ceil(k * tokens * capacity_factor / num_experts); tokens beyond an
expert's capacity are dropped (their combine weight is zero, the
residual path carries them); combine weights renormalize over the
selected experts. An auxiliary load-balancing loss (mean gate fraction
x mean dispatch fraction x num_experts, Switch eq. 4) is returned for
the caller to add.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import EXPERT_AXIS

__all__ = ["MoEConfig", "init_moe_params", "moe_ffn",
           "moe_param_specs"]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_hidden: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: object = jnp.float32

    def capacity(self, tokens):
        return max(int(np.ceil(self.top_k * tokens
                               * self.capacity_factor
                               / self.num_experts)), 1)


def init_moe_params(rng, cfg):
    kg, k1, k2 = jax.random.split(rng, 3)
    s1 = 1.0 / np.sqrt(cfg.d_model)
    s2 = 1.0 / np.sqrt(cfg.d_hidden)
    return {
        "gate_w": (jax.random.normal(kg, (cfg.d_model, cfg.num_experts))
                   * s1).astype(jnp.float32),
        "w1": (jax.random.normal(
            k1, (cfg.num_experts, cfg.d_model, cfg.d_hidden))
            * s1).astype(jnp.float32),
        "b1": jnp.zeros((cfg.num_experts, cfg.d_hidden), jnp.float32),
        "w2": (jax.random.normal(
            k2, (cfg.num_experts, cfg.d_hidden, cfg.d_model))
            * s2).astype(jnp.float32),
        "b2": jnp.zeros((cfg.num_experts, cfg.d_model), jnp.float32),
    }


def moe_param_specs():
    """PartitionSpecs: experts shard over the "expert" axis; the gate
    replicates (every token scores every expert)."""
    return {
        "gate_w": P(),
        "w1": P(EXPERT_AXIS, None, None),
        "b1": P(EXPERT_AXIS, None),
        "w2": P(EXPERT_AXIS, None, None),
        "b2": P(EXPERT_AXIS, None),
    }


def moe_sharding_spec(mesh=None):
    """The MoE placement as the unified ShardingSpec (parallel/spec.py)
    — same entries as ``moe_param_specs``, usable for executor interop
    and ``checkpoint_axes`` (experts tile dim 0 over "expert")."""
    from paddle_tpu.parallel.spec import ShardingSpec
    return ShardingSpec(mesh, params=moe_param_specs())


def _top_k_mask(gates, k):
    """[T, E] gate probs -> (positions [T, k] int, onehot [T, k, E])."""
    _, idx = jax.lax.top_k(gates, k)
    onehot = jax.nn.one_hot(idx, gates.shape[-1], dtype=gates.dtype)
    return idx, onehot


def moe_ffn(params, cfg, x, mesh=None):
    """x: [..., T, d_model] (leading dims flattened as tokens).
    Returns (y, aux_loss). Under a mesh with an "expert" axis and
    params placed per moe_param_specs, the ecd/ted einsums lower to
    all_to_all dispatch/combine over ICI."""
    shape = x.shape
    t = int(np.prod(shape[:-1]))
    xt = x.reshape(t, cfg.d_model).astype(jnp.float32)
    e, c = cfg.num_experts, cfg.capacity(t)

    gates = jax.nn.softmax(xt @ params["gate_w"], axis=-1)     # [T, E]
    _, sel = _top_k_mask(gates, cfg.top_k)                     # [T,K,E]

    # position of each (token, k) inside its expert's capacity bucket:
    # cumulative count of prior claims on that expert. GShard/Switch
    # priority order: ALL top-1 claims outrank any top-2 claim, so the
    # flatten must be k-major ([K,T,E]) before the cumsum — a
    # token-major flatten would let an early token's 2nd choice evict a
    # later token's 1st choice.
    claims = sel.transpose(1, 0, 2).reshape(cfg.top_k * t, e)  # [K*T, E]
    pos = (jnp.cumsum(claims, axis=0) - claims)            # claims before
    pos = jnp.sum(pos * claims, axis=-1).reshape(cfg.top_k, t).T
    within = (pos < c).astype(gates.dtype)                 # capacity drop
    kept = sel * within[..., None]                         # [T, K, E]

    # dispatch tensor [T, E, C]: claim -> capacity slot one-hot
    slot = jax.nn.one_hot(pos.astype(jnp.int32), c,
                          dtype=gates.dtype)               # [T, K, C]
    dispatch = jnp.einsum("tke,tkc->tec", kept, slot)      # [T, E, C]

    # combine weights: gate prob of each kept claim, renormalized over
    # the token's kept experts
    gk = jnp.einsum("te,tke->tk", gates, kept)             # [T, K]
    denom = jnp.maximum(jnp.sum(gk, axis=-1, keepdims=True), 1e-9)
    gk = gk / denom
    combine = jnp.einsum("tk,tke,tkc->tec", gk, kept, slot)

    # route -> expert FFN -> return (all_to_all under GSPMD)
    xin = jnp.einsum("tec,td->ecd", dispatch, xt)          # [E, C, D]
    if mesh is not None and EXPERT_AXIS in mesh.shape:
        xin = jax.lax.with_sharding_constraint(
            xin, NamedSharding(mesh, P(EXPERT_AXIS, None, None)))
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xin, params["w1"])
                    + params["b1"][:, None, :])
    out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
        + params["b2"][:, None, :]
    if mesh is not None and EXPERT_AXIS in mesh.shape:
        out = jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(EXPERT_AXIS, None, None)))
    y = jnp.einsum("tec,ecd->td", combine, out)            # [T, D]

    # Switch aux loss: num_experts * sum_e (gate fraction * dispatch
    # fraction). The dispatch fraction uses the PRE-drop assignment
    # (`sel`, as Switch/GShard define it) — computing it post-drop
    # caps the overloaded expert's fraction at C/T, which masks (and
    # slightly rewards) collapse exactly when drops begin.
    frac_gates = jnp.mean(gates, axis=0)                   # [E]
    frac_tokens = jnp.mean(jnp.sum(sel, axis=1), axis=0)   # [E]
    aux = e * jnp.sum(frac_gates * frac_tokens) / cfg.top_k

    return y.reshape(shape).astype(x.dtype), aux
