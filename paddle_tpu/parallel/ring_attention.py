"""Ring attention + Ulysses (all-to-all) sequence/context parallelism.

The reference framework (2019-era) has no sequence parallelism — its
longest-sequence story is LoD variable-length batching (ref:
SURVEY §5.7; lod_tensor.h:110). This module is the TPU-native
long-context design the rebuild treats as first-class:

* ``ring_attention`` — blockwise attention with online-softmax
  accumulation; K/V blocks rotate around the "seq" mesh axis via
  ``lax.ppermute`` (ICI neighbor exchange), so the full sequence is never
  materialised on one chip. Memory per chip is O(S/n), compute overlaps
  the permute. (Liu et al., Ring Attention, 2023 — blockwise pattern.)
* ``ulysses_attention`` — DeepSpeed-Ulysses style: ``all_to_all``
  re-shards [B, S/n, H, D] -> [B, S, H/n, D], runs ordinary attention
  on full sequence with a head subset, and all-to-alls back. Cheaper at
  moderate S, needs H % n == 0.

Both are written for ``shard_map`` over a mesh carrying a "seq" axis
(see parallel/mesh.py) and are exact (up to fp error) vs full softmax
attention — tests compare against the dense reference on an 8-device
CPU mesh.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from paddle_tpu.parallel._compat import shard_map

from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS

_NEG_INF = -1e30


def _block_attn(q, k, v, bias, scale):
    """One (q-block, kv-block) partial attention step.

    q: [B, Sq, H, D]; k,v: [B, Sk, H, D] — any dtype (bf16 stays bf16 on
    the MXU; accumulation and softmax stats are fp32 via
    preferred_element_type). bias: broadcastable to [B, H, Sq, Sk] or
    None. Returns (o_unnorm fp32 [B,Sq,H,D], m fp32 [B,H,Sq],
    l fp32 [B,H,Sq]) — unnormalised output, row max, row sum-exp.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def _combine(carry, o, m, l):
    """Online-softmax merge of a new partial block into the running
    (o_acc, m_acc, l_acc)."""
    o_acc, m_acc, l_acc = carry
    m_new = jnp.maximum(m_acc, m)
    alpha = jnp.exp(m_acc - m_new)   # rescale old
    beta = jnp.exp(m - m_new)        # rescale new
    l_new = l_acc * alpha + l * beta
    o_new = (o_acc * alpha[..., None].swapaxes(1, 2)
             + o * beta[..., None].swapaxes(1, 2))
    return o_new, m_new, l_new


def ring_attention_local(q, k, v, *, axis_name=SEQ_AXIS, causal=False,
                         key_padding_mask=None, scale=None):
    """Ring attention body — call INSIDE shard_map.

    q, k, v: [B, S_local, H, D] — the local sequence shard.
    key_padding_mask: [B, S_local] bool/0-1, True/1 = attend (rotates
      with K/V). causal: mask by absolute positions across shards.
    Returns [B, S_local, H, D].
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q_pos = idx * s_local + jnp.arange(s_local)           # absolute q rows
    perm = [(i, (i + 1) % n) for i in range(n)]           # shift kv right

    # Derive initial carries FROM q so they inherit q's varying mesh axes
    # (jax>=0.7 shard_map rejects fori_loop carries whose varying-axis
    # sets change between input and output). Accumulators are fp32
    # regardless of q's dtype (online-softmax stats need the range).
    masked = key_padding_mask is not None
    if masked:
        zero_bs = (q[:, :, 0, 0] * 0.0).astype(jnp.float32)  # [B, S_local]
        kpm = key_padding_mask.astype(jnp.float32) + zero_bs

    o_acc = (q * 0.0).astype(jnp.float32)
    zero_bhs = (jnp.moveaxis(q[..., 0], -1, 1) * 0.0       # [B, H, S_local]
                ).astype(jnp.float32)
    m_acc = zero_bhs + _NEG_INF
    l_acc = zero_bhs

    def block_bias(i, kpm_cur):
        # kv block currently held arrived from device (idx - i); its
        # absolute positions are ((idx - i) mod n) * s_local + arange.
        bias = None
        if kpm_cur is not None:
            bias = jnp.where(kpm_cur[:, None, None, :] > 0, 0.0, _NEG_INF)
        if causal:
            src = (idx - i) % n
            k_pos = src * s_local + jnp.arange(s_local)
            cmask = q_pos[:, None] >= k_pos[None, :]       # [Sq, Sk]
            cbias = jnp.where(cmask[None, None], 0.0, _NEG_INF)
            bias = cbias if bias is None else bias + cbias
        return bias

    if masked:
        def step(i, carry):
            o_acc, m_acc, l_acc, k, v, kpm = carry
            o, m, l = _block_attn(q, k, v, block_bias(i, kpm), scale)
            o_acc, m_acc, l_acc = _combine((o_acc, m_acc, l_acc), o, m, l)
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
            kpm = lax.ppermute(kpm, axis_name, perm)
            return o_acc, m_acc, l_acc, k, v, kpm

        o_acc, m_acc, l_acc, _, _, _ = lax.fori_loop(
            0, n, step, (o_acc, m_acc, l_acc, k, v, kpm))
    else:
        # maskless: no mask carry, no per-step mask permute or bias build
        def step(i, carry):
            o_acc, m_acc, l_acc, k, v = carry
            o, m, l = _block_attn(q, k, v, block_bias(i, None), scale)
            o_acc, m_acc, l_acc = _combine((o_acc, m_acc, l_acc), o, m, l)
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
            return o_acc, m_acc, l_acc, k, v

        o_acc, m_acc, l_acc, _, _ = lax.fori_loop(
            0, n, step, (o_acc, m_acc, l_acc, k, v))
    return (o_acc / l_acc[..., None].swapaxes(1, 2)).astype(q.dtype)


def ring_attention(mesh, q, k, v, *, causal=False, key_padding_mask=None,
                   scale=None, seq_axis=SEQ_AXIS, data_axis=DATA_AXIS,
                   model_axis=MODEL_AXIS):
    """shard_map wrapper: q,k,v are global [B, S, H, D] arrays; batch
    sharded over "data", sequence over "seq", heads over "model"."""
    qkv_spec = P(data_axis, seq_axis, model_axis, None)
    mask_spec = P(data_axis, seq_axis)
    body = functools.partial(ring_attention_local, causal=causal,
                             scale=scale, axis_name=seq_axis)

    if key_padding_mask is None:
        def f(q, k, v):
            return body(q, k, v)
        return shard_map(f, mesh=mesh,
                         in_specs=(qkv_spec, qkv_spec, qkv_spec),
                         out_specs=qkv_spec)(q, k, v)

    def f(q, k, v, kpm):
        return body(q, k, v, key_padding_mask=kpm)
    return shard_map(f, mesh=mesh,
                     in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
                     out_specs=qkv_spec)(q, k, v, key_padding_mask)


def ulysses_attention_local(q, k, v, *, axis_name=SEQ_AXIS, causal=False,
                            key_padding_mask=None, scale=None):
    """Ulysses body — call INSIDE shard_map.

    q,k,v: [B, S_local, H, D] with H % axis_size == 0. all_to_all to
    [B, S, H_local, D], dense attention, all_to_all back.
    """
    n = lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def seq2head(t):   # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(t):   # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    s_full = s_local * n
    bias = None
    if key_padding_mask is not None:
        kpm = lax.all_gather(key_padding_mask.astype(jnp.float32),
                             axis_name, axis=1, tiled=True)  # [B, S]
        bias = jnp.where(kpm[:, None, None, :] > 0, 0.0, _NEG_INF)
    if causal:
        pos = jnp.arange(s_full)
        cmask = pos[:, None] >= pos[None, :]
        cbias = jnp.where(cmask[None, None], 0.0, _NEG_INF)
        bias = cbias if bias is None else bias + cbias

    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg) * scale
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vg)
    return head2seq(o)


def ulysses_attention(mesh, q, k, v, *, causal=False, key_padding_mask=None,
                      scale=None, seq_axis=SEQ_AXIS, data_axis=DATA_AXIS):
    """shard_map wrapper for Ulysses; the seq-axis size must divide the
    head count (H % n_seq == 0 — all_to_all splits the head dim). Heads
    are NOT simultaneously sharded over "model" here (Ulysses uses the
    head dim as its transport dim)."""
    qkv_spec = P(data_axis, seq_axis, None, None)
    mask_spec = P(data_axis, seq_axis)
    body = functools.partial(ulysses_attention_local, causal=causal,
                             scale=scale, axis_name=seq_axis)
    if key_padding_mask is None:
        def f(q, k, v):
            return body(q, k, v)
        return shard_map(f, mesh=mesh,
                         in_specs=(qkv_spec, qkv_spec, qkv_spec),
                         out_specs=qkv_spec)(q, k, v)

    def f(q, k, v, kpm):
        return body(q, k, v, key_padding_mask=kpm)
    return shard_map(f, mesh=mesh,
                     in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
                     out_specs=qkv_spec)(q, k, v, key_padding_mask)


def full_attention_reference(q, k, v, *, causal=False,
                             key_padding_mask=None, scale=None):
    """Dense softmax attention on one device — the correctness oracle."""
    b, s, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if key_padding_mask is not None:
        att = att + jnp.where(
            key_padding_mask[:, None, None, :] > 0, 0.0, _NEG_INF)
    if causal:
        pos = jnp.arange(s)
        att = att + jnp.where(pos[:, None] >= pos[None, :],
                              0.0, _NEG_INF)[None, None]
    p = jax.nn.softmax(att, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
