"""Deep Gradient Compression (DGC) for cross-slice gradients.

Parity: the reference's DGC path — dgc_op.cc (top-k select + error
feedback), SparseAllReduceOpHandle (details/sparse_all_reduce_op_handle.h)
and DGCMomentumOptimizer (optimizer.py:787).

TPU-first shape: on ICI, gradients are cheap to all-reduce densely, so
DGC targets the DCN (cross-slice) hop. The compressed form here is a
dense masked tensor (top-k survivors, zeros elsewhere): XLA's collective
over a mostly-zero tensor is the idiomatic stand-in for the reference's
(index, value) NCCL payload, and the semantics — momentum correction,
error feedback, sparsity ramp-up — match the DGC recipe exactly.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["dgc_init", "dgc_compress", "dgc_allreduce_grads",
           "dgc_sparsity_at"]


def dgc_init(params):
    """Per-leaf state: momentum buffer u and error-feedback residual v
    (dgc_op.cc's U/V buffers)."""
    z = lambda p: jnp.zeros_like(p)
    return {"u": jax.tree.map(z, params), "v": jax.tree.map(z, params)}


def dgc_sparsity_at(step, rampup_begin_step=0, rampup_step=1,
                    sparsity=(0.75, 0.9375, 0.984375, 0.996, 0.999)):
    """Ramp-up schedule (DGCMomentumOptimizer's rampup args): before
    rampup_begin_step → 0 (no compression); then step through the
    sparsity list over rampup_step steps."""
    if step < rampup_begin_step:
        return 0.0
    i = (step - rampup_begin_step) * len(sparsity) // max(rampup_step, 1)
    return sparsity[min(i, len(sparsity) - 1)]


def _topk_mask(x, keep):
    flat = jnp.abs(x).reshape(-1)
    thresh = lax.top_k(flat, keep)[0][-1]
    return (jnp.abs(x) >= thresh).astype(x.dtype)


def dgc_compress(grad, u, v, sparsity, momentum=0.9):
    """One leaf: momentum-corrected accumulation then top-k selection.

    u' = m·u + g            (momentum correction)
    v' = v + u'             (error feedback accumulation)
    send = v' masked to top-(1-sparsity) fraction; v'' = v' - send.
    Returns (send, u', v'')."""
    u = momentum * u + grad
    v = v + u
    if sparsity <= 0.0:
        return v, u, jnp.zeros_like(v)
    keep = max(1, int(round(v.size * (1.0 - sparsity))))
    mask = _topk_mask(v, keep)
    send = v * mask
    return send, u, v - send


def dgc_allreduce_grads(grads, state, step, axis_name,
                        momentum=0.9, rampup_begin_step=0, rampup_step=1,
                        sparsity=(0.75, 0.9375, 0.984375, 0.996, 0.999)):
    """Compress every gradient leaf, pmean the sparse payloads across
    ``axis_name``, return (averaged grads, new state). Call inside
    shard_map/pmap (the SparseAllReduceOpHandle role)."""
    sp = dgc_sparsity_at(step, rampup_begin_step, rampup_step, sparsity)
    comp = functools.partial(dgc_compress, sparsity=sp, momentum=momentum)
    sends, us, vs = [], [], []
    flat_g, tree = jax.tree_util.tree_flatten(grads)
    flat_u = jax.tree_util.tree_leaves(state["u"])
    flat_v = jax.tree_util.tree_leaves(state["v"])
    for g, u, v in zip(flat_g, flat_u, flat_v):
        s, nu, nv = comp(g, u, v)
        sends.append(lax.pmean(s, axis_name))
        us.append(nu)
        vs.append(nv)
    unflat = lambda leaves: jax.tree_util.tree_unflatten(tree, leaves)
    return unflat(sends), {"u": unflat(us), "v": unflat(vs)}
