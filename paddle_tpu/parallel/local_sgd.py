"""LocalSGD: per-replica training with periodic parameter averaging.

Parity: transpiler/collective.py:263 LocalSGD (the reference rewrites the
program so each trainer steps independently and inserts a broadcast/
allreduce of PARAMETERS every k steps, instead of per-step gradient
allreduce).

TPU-first shape: params carry a leading replica axis sharded over the
data mesh axis; the per-replica step runs under shard_map (no collective
at all), and every ``k`` steps one pmean synchronises parameters — the
only cross-replica traffic. This is the communication-avoiding regime
LocalSGD exists for; on ICI it trades a per-step psum for a per-k pmean.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel._compat import CHECK_DISABLED as _CHECK_KW
from paddle_tpu.parallel._compat import shard_map
from paddle_tpu.parallel.mesh import DATA_AXIS, get_mesh

__all__ = ["LocalSGDTrainer"]


class LocalSGDTrainer:
    """loss_fn(params, batch) -> scalar loss; plain SGD per replica,
    parameter pmean every ``sync_steps`` steps."""

    def __init__(self, loss_fn, learning_rate=0.01, sync_steps=4,
                 mesh=None, axis_name=DATA_AXIS):
        self.loss_fn = loss_fn
        self.lr = learning_rate
        self.k = int(sync_steps)
        self.mesh = mesh or get_mesh()
        self.axis = axis_name
        self._step = None

    def init(self, params):
        """Replicate initial params to a leading replica axis
        [n_replicas, ...] (all replicas start equal — the reference's
        startup broadcast, transpiler/collective.py _transpile_startup)."""
        n = self.mesh.shape[self.axis]
        stacked = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), params)
        return {"params": stacked, "step": jnp.zeros((), jnp.int32)}

    def _build(self, state, batch):
        mesh = self.mesh
        ax = self.axis
        k = self.k
        lr = self.lr
        loss_fn = self.loss_fn

        pspec = jax.tree.map(lambda _: P(ax), state["params"])
        bspec = jax.tree.map(lambda _: P(ax), batch)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(pspec, P(), bspec), out_specs=(P(ax), P()),
            **_CHECK_KW)
        def step(params, stepno, local_batch):
            p = jax.tree.map(lambda t: t[0], params)   # this replica's
            loss, grads = jax.value_and_grad(loss_fn)(p, local_batch)
            p = jax.tree.map(lambda t, g: t - lr * g, p, grads)
            do_sync = ((stepno + 1) % k) == 0
            p = jax.tree.map(
                lambda t: lax.cond(do_sync,
                                   lambda x: lax.pmean(x, ax),
                                   lambda x: x, t), p)
            mean_loss = lax.pmean(loss, ax)
            return jax.tree.map(lambda t: t[None], p), mean_loss

        return jax.jit(step)

    def train_step(self, state, batch):
        """batch leading dim divides the replica count. Returns
        (mean loss, new state)."""
        if self._step is None:
            self._step = self._build(state, batch)
        params, loss = self._step(state["params"], state["step"], batch)
        return loss, {"params": params, "step": state["step"] + 1}

    def sync_params(self, state):
        """Final average (the reference's end-of-training allreduce)."""
        avg = jax.tree.map(lambda t: jnp.mean(t, axis=0), state["params"])
        return avg
