"""Distributed process environment.

Parity: the reference's env-var identity wiring (PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_CURRENT_ENDPOINT…
ref: python/paddle/fluid/dygraph/parallel.py:54-82, test_dist_base.py:429)
and `paddle.distributed.launch` (launch.py:132). On TPU pods, JAX's
runtime provides process_index/process_count from the scheduler, so env
vars are a fallback for CPU-multihost testing.
"""

import os

import jax

__all__ = ["ParallelEnv", "get_rank", "get_world_size", "init_parallel_env"]


class ParallelEnv:
    """dygraph.parallel.ParallelEnv parity."""

    def __init__(self):
        self._rank = int(os.environ.get(
            "PADDLE_TRAINER_ID", jax.process_index()))
        self._world = int(os.environ.get(
            "PADDLE_TRAINERS_NUM", jax.process_count()))
        self._endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._endpoints = os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def local_rank(self):
        return self._rank

    @property
    def nranks(self):
        return self._world

    @property
    def dev_id(self):
        return 0  # one process drives all local chips under JAX

    @property
    def current_endpoint(self):
        return self._endpoint

    @property
    def trainer_endpoints(self):
        return self._endpoints


def get_rank():
    return ParallelEnv().local_rank


def get_world_size():
    return ParallelEnv().nranks


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Multi-host bring-up: the analog of gen_nccl_id + comm init
    (ref: distributed_ops/gen_nccl_id_op.cc — TPU needs no id exchange;
    jax.distributed handles the DCN rendezvous)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    return ParallelEnv()


class ParallelStrategy:
    """dygraph.parallel.ParallelStrategy parity (the prepare_context
    product): carries world size + endpoints."""

    def __init__(self, nranks=1, local_rank=0, trainer_endpoints=(),
                 current_endpoint=""):
        self.nranks = nranks
        self.local_rank = local_rank
        self.trainer_endpoints = list(trainer_endpoints)
        self.current_endpoint = current_endpoint


def prepare_context(strategy=None):
    """dygraph.parallel.prepare_context parity (ref
    dygraph/parallel.py:30): assemble the ParallelStrategy from the
    process env. On TPU there is no NCCL context to initialize — the
    runtime owns topology — so this is pure bookkeeping."""
    if strategy is not None:
        return strategy
    env = ParallelEnv()
    return ParallelStrategy(env.nranks, env.local_rank,
                            env.trainer_endpoints, env.current_endpoint)


class DataParallel:
    """dygraph.parallel.DataParallel parity (ref dygraph/parallel.py:84)
    in functional form: wraps an nn.Layer; ``scale_loss`` divides by the
    replica count and ``apply_collective_grads`` mean-reduces a GRADIENT
    TREE across replicas (the reference mutates grads in place; grads
    are values here). scale_loss + psum == pmean, matching the
    reference's scale-then-allreduce pair.

    Inside SPMD (shard_map over the data axis) the reduction is
    lax.pmean over ``axis_name``; outside any mapped context with
    nranks == 1 both calls are identity — the reference's
    non-data-parallel fallback.
    """

    def __init__(self, layers, strategy=None, axis_name="data"):
        self._layers = layers
        self._strategy = strategy or prepare_context()
        self._axis = axis_name

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):       # delegate init/apply/sublayers
        if name.startswith("_"):        # incl. unpickle probing before
            raise AttributeError(name)  # __dict__ exists — no recursion
        return getattr(self.__dict__["_layers"], name)

    def scale_loss(self, loss):
        n = max(self._strategy.nranks, 1)
        return loss / n if n > 1 else loss

    def apply_collective_grads(self, grads):
        """grads tree -> psum'd tree over the data axis (use inside
        shard_map/pmap; with scale_loss applied first the result is the
        cross-replica mean, ref parallel.py:150,171)."""
        if max(self._strategy.nranks, 1) == 1:
            return grads
        from paddle_tpu.parallel.collective import psum
        return jax.tree.map(
            lambda g: psum(g, axis_name=self._axis), grads)


__all__ += ["ParallelStrategy", "prepare_context", "DataParallel"]
