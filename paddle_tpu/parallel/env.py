"""Distributed process environment.

Parity: the reference's env-var identity wiring (PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_CURRENT_ENDPOINT…
ref: python/paddle/fluid/dygraph/parallel.py:54-82, test_dist_base.py:429)
and `paddle.distributed.launch` (launch.py:132). On TPU pods, JAX's
runtime provides process_index/process_count from the scheduler, so env
vars are a fallback for CPU-multihost testing.
"""

import os

import jax

__all__ = ["ParallelEnv", "get_rank", "get_world_size", "init_parallel_env"]


class ParallelEnv:
    """dygraph.parallel.ParallelEnv parity."""

    def __init__(self):
        self._rank = int(os.environ.get(
            "PADDLE_TRAINER_ID", jax.process_index()))
        self._world = int(os.environ.get(
            "PADDLE_TRAINERS_NUM", jax.process_count()))
        self._endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self._endpoints = os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def local_rank(self):
        return self._rank

    @property
    def nranks(self):
        return self._world

    @property
    def dev_id(self):
        return 0  # one process drives all local chips under JAX

    @property
    def current_endpoint(self):
        return self._endpoint

    @property
    def trainer_endpoints(self):
        return self._endpoints


def get_rank():
    return ParallelEnv().local_rank


def get_world_size():
    return ParallelEnv().nranks


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Multi-host bring-up: the analog of gen_nccl_id + comm init
    (ref: distributed_ops/gen_nccl_id_op.cc — TPU needs no id exchange;
    jax.distributed handles the DCN rendezvous)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    return ParallelEnv()
