"""jax version-compat shims shared by the parallel modules.

Renames this codebase has to straddle (the container pin is older than
the APIs some call sites were written against):

- ``jax.shard_map`` is top-level only in newer jax; the pinned
  jax 0.4.37 has **no** ``jax.shard_map`` and ships it as
  ``jax.experimental.shard_map.shard_map``. The first use of that
  fallback warns once per process (key ``"shard_map_fallback"``) so a
  run's logs record which code path actually executed.
- jax>=0.8 renamed shard_map's ``check_rep`` kwarg to ``check_vma``;
  the kwarg name is probed once, at import.

Because the pin has no stable shard_map, the unified mesh partitioner
(parallel/spec.py) does NOT build on it: sharding annotations route
through the pjit path — committed input shardings plus
``sharding_constraint`` below (``jax.lax.with_sharding_constraint``,
which jax.jit IS pjit for on this pin). ``HAS_NATIVE_SHARD_MAP`` lets
tests pin which path runs.

Import from here instead of re-probing per module — five drifting
copies of version detection is how compat bugs are born.
"""

import inspect as _inspect

import jax as _jax

try:
    from jax import shard_map as _shard_map_impl
    HAS_NATIVE_SHARD_MAP = True
except ImportError:  # older jax (the 0.4.37 container pin)
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    HAS_NATIVE_SHARD_MAP = False

SHARD_MAP_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map_impl).parameters
    else "check_rep")

#: splat into a shard_map call to disable replication checking under
#: either kwarg spelling: ``shard_map(f, ..., **CHECK_DISABLED)``
CHECK_DISABLED = {SHARD_MAP_CHECK_KW: False}


def shard_map(*args, **kwargs):
    """``jax.shard_map`` when the pin has it; else the
    ``jax.experimental.shard_map`` fallback, announced once per process
    the first time it actually engages (a silent fallback left runs
    with no record of which implementation they exercised)."""
    if not HAS_NATIVE_SHARD_MAP:
        from paddle_tpu.core.enforce import warn_once
        warn_once(
            "shard_map_fallback",
            "jax has no top-level jax.shard_map on this pin "
            f"(jax {_jax.__version__}): falling back to "
            "jax.experimental.shard_map. Spec-driven sharding "
            "(parallel/spec.py) routes through pjit/"
            "with_sharding_constraint instead and does not depend on "
            "this fallback.")
    return _shard_map_impl(*args, **kwargs)


def sharding_constraint(x, mesh, spec):
    """Pin ``x``'s sharding inside a jitted computation via the pjit
    path (``jax.lax.with_sharding_constraint``) — the lowering the
    unified ShardingSpec uses for the compiled device segments, valid
    on every supported jax (no shard_map involved). ``spec`` may be a
    ``PartitionSpec`` or an already-built ``NamedSharding``."""
    from jax.sharding import NamedSharding
    if not isinstance(spec, NamedSharding):
        spec = NamedSharding(mesh, spec)
    return _jax.lax.with_sharding_constraint(x, spec)


__all__ = ["shard_map", "sharding_constraint", "SHARD_MAP_CHECK_KW",
           "CHECK_DISABLED", "HAS_NATIVE_SHARD_MAP"]
