"""jax version-compat shims shared by the parallel modules.

Two renames this codebase has to straddle (the container pin is older
than the APIs some call sites were written against):

- ``jax.shard_map`` is top-level only in newer jax; older jax ships it
  as ``jax.experimental.shard_map.shard_map``.
- jax>=0.8 renamed shard_map's ``check_rep`` kwarg to ``check_vma``;
  the kwarg name is probed once, at import.

Import from here instead of re-probing per module — five drifting
copies of version detection is how compat bugs are born.
"""

import inspect as _inspect

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

SHARD_MAP_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(shard_map).parameters
    else "check_rep")

#: splat into a shard_map call to disable replication checking under
#: either kwarg spelling: ``shard_map(f, ..., **CHECK_DISABLED)``
CHECK_DISABLED = {SHARD_MAP_CHECK_KW: False}

__all__ = ["shard_map", "SHARD_MAP_CHECK_KW", "CHECK_DISABLED"]
