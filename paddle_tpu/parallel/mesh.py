"""Device mesh bookkeeping.

Replaces NCCLContextMap / NCCLCommunicator ring bookkeeping
(ref: platform/nccl_helper.h:90,179 — flat + hierarchical comm groups;
platform/collective_helper.h named comms). On TPU the runtime knows the
topology; a mesh names axes (data/model/pipe/seq) and XLA lowers
collectives onto ICI rings per axis. The BuildStrategy knobs
(hierarchical allreduce, multi-ring, ref: details/build_strategy.h:129-138)
correspond to how axes are laid out over the physical topology.

Canonical axis names:
  "data"  — data parallel (the reference's trainer replicas)
  "model" — tensor/op parallelism (not in the reference; free via GSPMD)
  "pipe"  — pipeline stages (ref: PipelineTrainer)
  "seq"   — sequence/context parallelism (ring attention)
"""

import contextlib
from dataclasses import dataclass, field

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"


@dataclass
class MeshConfig:
    data: int = -1     # -1 = all remaining devices
    model: int = 1
    pipe: int = 1
    seq: int = 1
    axis_order: tuple = (DATA_AXIS, PIPE_AXIS, MODEL_AXIS, SEQ_AXIS)


def mesh_shape_for(n_devices, cfg):
    sizes = {DATA_AXIS: cfg.data, MODEL_AXIS: cfg.model,
             PIPE_AXIS: cfg.pipe, SEQ_AXIS: cfg.seq}
    fixed = 1
    for a, s in sizes.items():
        if s != -1:
            fixed *= s
    for a in sizes:
        if sizes[a] == -1:
            sizes[a] = n_devices // fixed
    return tuple(sizes[a] for a in cfg.axis_order)


def make_mesh(config=None, devices=None):
    """Build a Mesh over the given (default: all) devices.

    Axis layout note: the innermost mesh axis maps to adjacent devices,
    so put the highest-bandwidth-demand axis ("model") innermost — the
    analog of the reference's hierarchical inter/exter ring split
    (parallel_executor.cc:158-180)."""
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    shape = mesh_shape_for(len(devices), config)
    used = 1
    for s in shape:
        used *= s
    arr = np.array(devices[:used]).reshape(shape)
    return Mesh(arr, config.axis_order)


_current_mesh = [None]


def set_mesh(mesh):
    _current_mesh[0] = mesh
    return mesh


def get_mesh():
    if _current_mesh[0] is None:
        set_mesh(make_mesh())
    return _current_mesh[0]


@contextlib.contextmanager
def mesh_guard(mesh):
    old = _current_mesh[0]
    _current_mesh[0] = mesh
    try:
        yield mesh
    finally:
        _current_mesh[0] = old


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, P(*spec))
