"""Device mesh bookkeeping.

Replaces NCCLContextMap / NCCLCommunicator ring bookkeeping
(ref: platform/nccl_helper.h:90,179 — flat + hierarchical comm groups;
platform/collective_helper.h named comms). On TPU the runtime knows the
topology; a mesh names axes (data/model/pipe/seq) and XLA lowers
collectives onto ICI rings per axis. The BuildStrategy knobs
(hierarchical allreduce, multi-ring, ref: details/build_strategy.h:129-138)
correspond to how axes are laid out over the physical topology.

Canonical axis names:
  "data"  — data parallel (the reference's trainer replicas)
  "model" — tensor/op parallelism (not in the reference; free via GSPMD)
  "pipe"  — pipeline stages (ref: PipelineTrainer)
  "seq"   — sequence/context parallelism (ring attention)
"""

import contextlib
from dataclasses import dataclass, field

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"    # MoE expert parallelism (all_to_all routing)
DCN_AXIS = "dcn_data"     # cross-slice data parallelism (rides DCN)


@dataclass
class MeshConfig:
    data: int = -1     # -1 = all remaining devices
    model: int = 1
    pipe: int = 1
    seq: int = 1
    # cross-slice (DCN) data-parallel degree. > 1 prepends an OUTERMOST
    # "dcn_data" axis: gradient sync over ("dcn_data", "data") is then
    # hierarchical — XLA reduces within each slice over ICI first and
    # crosses DCN once per slice, the TPU-native form of the
    # reference's inter/exter two-level rings
    # (nccl_helper.h:179 NCCLCommunicator, build_strategy.h:132-138
    # use_hierarchical_allreduce).
    dcn_data: int = 1
    # MoE expert parallelism; > 1 appends an "expert" axis to
    # axis_order (kept out of the default order so non-MoE meshes are
    # unchanged)
    expert: int = 1
    axis_order: tuple = (DATA_AXIS, PIPE_AXIS, MODEL_AXIS, SEQ_AXIS)


def _effective_order(cfg):
    order = tuple(cfg.axis_order)
    if max(getattr(cfg, "expert", 1), 1) > 1 and EXPERT_AXIS not in order:
        order = order + (EXPERT_AXIS,)
    return order


def mesh_shape_for(n_devices, cfg):
    sizes = {DATA_AXIS: cfg.data, MODEL_AXIS: cfg.model,
             PIPE_AXIS: cfg.pipe, SEQ_AXIS: cfg.seq,
             EXPERT_AXIS: max(getattr(cfg, "expert", 1), 1)}
    order = _effective_order(cfg)
    fixed = max(getattr(cfg, "dcn_data", 1), 1)
    for a in order:
        if sizes.get(a, 1) != -1:
            fixed *= sizes.get(a, 1)
    for a in sizes:
        if sizes[a] == -1:
            sizes[a] = n_devices // fixed
    return tuple(sizes.get(a, 1) for a in order)


def make_mesh(config=None, devices=None):
    """Build a Mesh over the given (default: all) devices.

    Axis layout policy (the DCN-vs-ICI placement the reference tunes
    with hierarchical/multi-ring knobs, build_strategy.h:129-138):
    - the OUTERMOST axis strides across the largest device distances —
      config.dcn_data puts cross-slice data parallelism there, so only
      that axis's collectives cross DCN;
    - the INNERMOST mesh axis maps to adjacent devices, so the
      highest-bandwidth-demand axis ("model", default axis_order) sits
      innermost on the tightest ICI ring (the inter/exter ring split of
      parallel_executor.cc:158-180).
    On real multi-slice TPU fleets the hybrid layout is taken from the
    platform topology (mesh_utils.create_hybrid_device_mesh) when
    available; virtual/CPU platforms use the order of jax.devices().
    """
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    dcn = max(getattr(config, "dcn_data", 1), 1)
    shape = mesh_shape_for(len(devices), config)
    names = _effective_order(config)
    if dcn > 1:
        names = (DCN_AXIS,) + names
        per_slice = tuple(shape)
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        if len(slice_ids - {None}) > 1:
            # real multi-slice fleet: the hybrid layout must respect
            # slice boundaries (errors here are config errors and must
            # surface — a silent reshape would route intra-slice
            # collectives over DCN)
            from jax.experimental import mesh_utils
            arr = mesh_utils.create_hybrid_device_mesh(
                (1,) + per_slice,
                dcn_mesh_shape=(dcn,) + (1,) * len(per_slice),
                devices=devices)
            return Mesh(arr, names)
        # single-slice / virtual platforms: outermost-axis reshape
        shape = (dcn,) + per_slice
    used = 1
    for s in shape:
        used *= s
    arr = np.array(devices[:used]).reshape(shape)
    return Mesh(arr, names)


def data_axes(mesh):
    """The data-parallel axes present in the mesh, DCN-outermost:
    gradient psum over this tuple is the hierarchical allreduce."""
    return tuple(a for a in (DCN_AXIS, DATA_AXIS)
                 if a in mesh.shape)


_current_mesh = [None]


def set_mesh(mesh):
    _current_mesh[0] = mesh
    return mesh


def get_mesh():
    if _current_mesh[0] is None:
        set_mesh(make_mesh())
    return _current_mesh[0]


@contextlib.contextmanager
def mesh_guard(mesh):
    old = _current_mesh[0]
    _current_mesh[0] = mesh
    try:
        yield mesh
    finally:
        _current_mesh[0] = old


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, P(*spec))
