"""Reader decorators — composable python data pipelines.

Parity: python/paddle/reader/decorator.py (map_readers, shuffle:82,
chain, compose, buffered:196, firstn, xmap_readers:267,
multiprocess_reader:360) and fluid.io.cache. A reader is a zero-arg
callable returning an iterator; decorators wrap readers — same contract
as the reference so user data code ports directly. The native C++
high-throughput pipeline is paddle_tpu/data/native.py; these python
decorators are the compatibility/composability layer.
"""

import itertools
import queue
import random as pyrandom
import threading

__all__ = [
    "map_readers", "shuffle", "chain", "compose", "buffered", "firstn",
    "xmap_readers", "cache", "multiprocess_reader",
    "ComposeNotAligned", "PipeReader", "Fake", "bucketed_batch",
]


class ComposeNotAligned(ValueError):
    """compose() inputs ended at different lengths with
    check_alignment=True (ref: python/paddle/reader/decorator.py)."""


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size, seed=None):
    """Buffered shuffle. ``seed`` gives a private, reproducible RNG so
    workers can decorrelate deterministically; default keeps the
    reference's behavior (process-global random module,
    python/paddle/reader/decorator.py shuffle)."""
    rng = pyrandom.Random(seed) if seed is not None else pyrandom

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()
    return reader


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        _end = object()           # sentinel: None is a legal sample value
        if not check_alignment:
            for outputs in itertools.zip_longest(*rs, fillvalue=_end):
                yield sum((make_tuple(o) for o in outputs
                           if o is not _end), ())
            return
        for outputs in itertools.zip_longest(*rs, fillvalue=_end):
            if any(o is _end for o in outputs):
                raise ComposeNotAligned(
                    "readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())
    return reader


def buffered(reader, size):
    """Background-thread prefetch (decorator.py:196)."""
    class _End:
        pass

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def worker():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map over a reader with worker threads (decorator.py:267)."""
    end = object()

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feeder():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(end)

        results = {}
        lock = threading.Lock()

        def worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, d = item
                out_q.put((i, mapper(d)))

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()

        finished = 0
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                with lock:
                    results[item[0]] = item[1]
                while next_idx in results:
                    yield results.pop(next_idx)
                    next_idx += 1
        if order:
            while next_idx in results:
                yield results.pop(next_idx)
                next_idx += 1
    return xreader


def cache(reader):
    all_data = []
    cached = [False]

    def cache_reader():
        if not cached[0]:
            for d in reader():
                all_data.append(d)
                yield d
            cached[0] = True
        else:
            yield from all_data
    return cache_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Thread-based equivalent (TPU hosts favor threads feeding the
    device; the reference forks processes to dodge the GIL for python
    decoding — heavy decode belongs in the native pipeline instead)."""
    return chain(*readers) if len(readers) == 1 else _interleave(readers)


def _interleave(readers):
    def reader():
        its = [r() for r in readers]
        while its:
            nxt = []
            for it in its:
                try:
                    yield next(it)
                    nxt.append(it)
                except StopIteration:
                    pass
            its = nxt
    return reader


class PipeReader:
    """Stream records from a shell command's stdout (the reference reads
    HDFS cat pipes this way; ref python/paddle/reader/decorator.py
    PipeReader)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError(f"file_type must be plain or gzip, "
                            f"got {file_type!r}")
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type

    def get_line(self, cut_lines=True, line_break="\n"):
        import subprocess
        proc = subprocess.Popen(self.command, shell=True,
                                stdout=subprocess.PIPE)
        try:
            if self.file_type == "gzip":
                import zlib

                def new_decomp():
                    return zlib.decompressobj(32 + zlib.MAX_WBITS)
                decomp = new_decomp()

                def inflate(chunk):
                    # `hadoop fs -cat dir/*.gz` concatenates gzip
                    # MEMBERS: restart a decompressor on each member's
                    # trailing bytes or all shards after the first are
                    # silently dropped
                    nonlocal decomp
                    out = b""
                    while chunk:
                        out += decomp.decompress(chunk)
                        if not decomp.eof:
                            break
                        chunk = decomp.unused_data
                        decomp = new_decomp()
                    return out
            remained = b""
            while True:
                buf = proc.stdout.read(self.bufsize)
                if not buf:
                    break
                if self.file_type == "gzip":
                    buf = inflate(buf)
                if not cut_lines:
                    yield buf
                    continue
                buf = remained + buf
                lines = buf.split(line_break.encode())
                remained = lines.pop()
                for ln in lines:
                    yield ln.decode("utf-8", "replace")
            if cut_lines and remained:
                yield remained.decode("utf-8", "replace")
        finally:
            proc.stdout.close()
            rc = proc.wait()
            if rc != 0:
                raise RuntimeError(
                    f"PipeReader command failed (exit {rc}): "
                    f"{self.command}")


class Fake:
    """Caches the first batch of the decorated reader and replays it
    forever — the reference's IO-free benchmarking reader (ref
    decorator.py Fake)."""

    def __init__(self):
        self.data = None

    def __call__(self, reader, length):
        def fake_reader():
            if self.data is None:
                _empty = object()
                first = next(reader(), _empty)   # PEP 479: no bare next
                if first is _empty:
                    raise ValueError(
                        "Fake: decorated reader yielded no samples")
                self.data = first
            for _ in range(length):
                yield self.data
        return fake_reader


def bucketed_batch(reader, bucket_boundaries, batch_size, pad_value=0,
                   length_fn=None, drop_last=False, ragged_fields=None):
    """Bucketing-by-length — the TPU-native mitigation for LoD's
    "no padding" efficiency claim (SURVEY §7 hard part; core/lod.py
    points here). Samples are grouped into buckets by sequence length
    and every batch is padded to its BUCKET BOUNDARY, not the batch
    max, so under jit the shape set stays small and quantized:
    one shape per bucket, plus — for lengths beyond the last
    boundary — one shape per multiple of the last boundary actually
    observed, plus (when drop_last=False) the tail batches' ragged
    leading dims. With drop_last=True and lengths within the
    boundaries the count is exactly len(bucket_boundaries).

    reader: yields sample tuples of arrays. ragged_fields names the
    field indices to pad; when None the classification is inferred
    from the FIRST assembled batch (a field whose leading dim tracks
    the length in every sample) and then held fixed for the whole
    stream, so shapes never flip mid-epoch — pass ragged_fields
    explicitly when a fixed-size field's size could coincide with all
    lengths of the first batch.
    length_fn: sample -> int (default: len of the first field).

    Yields (fields..., lengths) — each padded field [B, boundary, ...],
    lengths [B] int32 (RaggedBatch(field, lengths) reassembles LoD
    semantics downstream).
    """
    import numpy as np
    bounds = sorted(int(b) for b in bucket_boundaries)
    if not bounds:
        raise ValueError("bucket_boundaries must be non-empty")
    lf = length_fn or (lambda s: len(s[0]))
    ragged_set = set(ragged_fields) if ragged_fields is not None else None

    def classify(buf):
        # sticky auto-classification from the first assembled batch:
        # a field is length-like if it tracks the length in EVERY
        # sample; held fixed afterwards so shapes never flip mid-epoch
        nonlocal ragged_set
        ragged_set = set()
        for i in range(len(buf[0][1])):
            fields = [np.asarray(s[i]) for _, s in buf]
            if all(f.ndim >= 1 and f.shape[0] == l
                   for f, (l, _) in zip(fields, buf)):
                ragged_set.add(i)

    def pad_batch(buf, boundary):
        n_fields = len(buf[0][1])
        lengths = np.array([l for l, _ in buf], np.int32)
        if ragged_set is None:
            classify(buf)
        out = []
        for i in range(n_fields):
            fields = [np.asarray(s[i]) for _, s in buf]
            if i in ragged_set:
                tail = fields[0].shape[1:]
                arr = np.full((len(buf), boundary) + tail, pad_value,
                              fields[0].dtype)
                for j, (l, _) in enumerate(buf):
                    arr[j, :l] = fields[j][:boundary]
                out.append(arr)
            else:
                out.append(np.stack(fields))
        out.append(lengths)
        return tuple(out)

    def overflow_boundary(buf):
        m = max(l for l, _ in buf)
        q = bounds[-1]
        return ((m + q - 1) // q) * q            # quantized shape set

    def bucketed():
        buckets = {}                     # boundary -> [(len, sample)]
        overflow = []
        for sample in reader():
            if not isinstance(sample, tuple):
                sample = (sample,)
            n = int(lf(sample))
            b = next((bd for bd in bounds if n <= bd), None)
            if b is None:
                overflow.append((n, sample))
                if len(overflow) == batch_size:
                    yield pad_batch(overflow, overflow_boundary(overflow))
                    overflow = []
                continue
            buf = buckets.setdefault(b, [])
            buf.append((n, sample))
            if len(buf) == batch_size:
                yield pad_batch(buf, b)
                buckets[b] = []
        if not drop_last:
            for b, buf in sorted(buckets.items()):
                if buf:
                    yield pad_batch(buf, b)
            if overflow:
                yield pad_batch(overflow, overflow_boundary(overflow))
    return bucketed
