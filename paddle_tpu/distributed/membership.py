"""Epoch-fenced elastic membership for the parameter-server fleet.

Parity target: the reference fleet's pslib downpour resharding
(framework/fleet — a production sparse-table fleet can grow or shrink
without restarting the job). This module is the *control plane* for
that: a monotonic **fleet epoch** pins a server list plus an explicit,
epoch-versioned shard map (replacing the static var->endpoint modulo
placement), and a resize is a two-phase migration driven by the
`launch_ps` coordinator:

  phase 1  the coordinator computes a delta plan (`plan_resize`) and
           asks each source server (MIGRATE_PLAN) to stream the moving
           units — whole dense vars and per-vshard slices of sparse
           tables — to their targets (MIGRATE_BEGIN/CHUNK/END, each
           chunk CRC-gated). Targets stage the state into durable
           shadow files (`psshadow_*`, published through the
           io_checkpoint publish/verify idiom, so a torn write is
           detected, never adopted).
  phase 2  the coordinator verifies every staged shadow, then performs
           the single atomic commit: publishing `fleet_epoch.json`.
           MIGRATE_COMMIT fans the new map out to the servers
           (idempotent — a server that misses it reconciles from the
           epoch file on respawn); sources retire moved units; clients
           carrying a stale epoch are fenced with WRONG_EPOCH and
           re-route (the PR-14 incarnation-token discipline, one level
           up).

Any failure before the epoch-file publish aborts: MIGRATE_ABORT
unfreezes the sources, staged shadows are swept, and the old epoch
stays in force — the coordinator retries with the same target epoch,
so a half-done migration is never observable.
"""

import io
import json
import os
import re
import socket
import time
import zlib

import numpy as np

from paddle_tpu.distributed import wire
from paddle_tpu import io_checkpoint as ioc

# sparse tables are sharded into a fixed number of virtual shards; a
# resize reassigns whole vshards, so the unit of migration is bounded
# and the map stays a small JSON object regardless of table size
NUM_VSHARDS = 8

EPOCH_FILE = "fleet_epoch.json"

_SHADOW_RE = re.compile(
    r"^psshadow_(?P<tag>[A-Za-z0-9_\-]+)\.ep(?P<epoch>\d+)\."
    r"(?P<unit>.+)\.npz$")


class MigrationError(Exception):
    """A migration attempt failed and was rolled back to the old epoch
    (the coordinator may retry; nothing half-applied is observable)."""


def vshard_of(ids):
    """Deterministic vshard index for each sparse id (multiplicative
    hash — splits consecutive id ranges instead of striding them)."""
    ids = np.asarray(ids, np.int64).astype(np.uint64)
    with np.errstate(over="ignore"):
        h = ids * np.uint64(0x9E3779B97F4A7C15)
        h ^= h >> np.uint64(29)
    return (h % np.uint64(NUM_VSHARDS)).astype(np.int64)


def dense_unit(name):
    return "d/" + name


def sparse_unit(table, v):
    return f"s/{table}/{int(v)}"


def parse_unit(unit):
    """-> ("d", var_name, None) or ("s", table_name, vshard)."""
    kind, rest = unit.split("/", 1)
    if kind == "d":
        return "d", rest, None
    table, v = rest.rsplit("/", 1)
    return "s", table, int(v)


def tag_of_ep(endpoint):
    """Filesystem-safe endpoint tag (matches ps._ps_tag)."""
    host, port = endpoint.rsplit(":", 1)
    return f"{host}_{port}".replace(".", "_")


def shadow_path(state_dir, tag, epoch, unit):
    safe = re.sub(r"[^A-Za-z0-9_.\-]", "_", unit)
    return os.path.join(state_dir,
                        f"psshadow_{tag}.ep{int(epoch)}.{safe}.npz")


def list_shadows(state_dir, tag=None):
    """[(path, tag, epoch, safe_unit)] for staged shadow files."""
    out = []
    try:
        names = os.listdir(state_dir)
    except OSError:
        return out
    for f in sorted(names):
        m = _SHADOW_RE.match(f)
        if m and (tag is None or m.group("tag") == tag):
            out.append((os.path.join(state_dir, f), m.group("tag"),
                        int(m.group("epoch")), m.group("unit")))
    return out


def pack_arrays(arrays):
    """npz-pack an arrays dict into a u8 wire blob + its crc32 (the
    SHUFFLE_PUSH blob idiom, plus the per-chunk CRC the migration
    protocol gates on)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    raw = np.frombuffer(buf.getvalue(), np.uint8)
    return raw, zlib.crc32(raw) & 0xFFFFFFFF


def unpack_blob(blob):
    """Inverse of pack_arrays -> {name: array}."""
    raw = np.ascontiguousarray(np.asarray(blob, np.uint8))
    with np.load(io.BytesIO(raw.tobytes()),
                 allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


# -- epoch file (THE commit point) ----------------------------------------

def epoch_file_path(state_dir):
    return os.path.join(state_dir, EPOCH_FILE)


def publish_epoch_file(state_dir, epoch, shard_map):
    """Atomically publish the committed epoch + map. This single
    os.replace IS the migration's commit point: everything before it
    is abortable staging, everything after is reconcilable catch-up."""
    ioc._publish_json_atomic(
        epoch_file_path(state_dir),
        {"epoch": int(epoch), "map": shard_map, "time": time.time()},
        "." + EPOCH_FILE + ".")
    ioc._fsync_dir(state_dir)


def load_epoch_file(state_dir):
    """Committed {"epoch", "map", "time"} or None when no resize has
    ever committed (epoch 0 — the implicit static-placement epoch)."""
    try:
        with open(epoch_file_path(state_dir)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except ValueError:
        # unreachable for our own atomic publishes; treat a mangled
        # hand-edited file as absent rather than wedging every respawn
        return None


# -- shard-map construction / resize planning -----------------------------

def initial_map(servers, dense_owner, sparse_owner):
    """Epoch-0 map from the static placement: dense var -> its hosting
    endpoint, every vshard of a table -> the table's hosting endpoint."""
    return {
        "epoch": 0,
        "servers": list(servers),
        "dense": dict(dense_owner),
        "sparse": {t: {str(v): ep for v in range(NUM_VSHARDS)}
                   for t, ep in sparse_owner.items()},
    }


def _balance_vshards(owners, servers):
    """Quota-balanced vshard assignment: keep the current owner while
    it is under quota, reassign overflow to the underfull server with
    the lowest index — minimal movement, fully deterministic."""
    s_count = len(servers)
    quota = {s: NUM_VSHARDS // s_count + (1 if i < NUM_VSHARDS % s_count
                                          else 0)
             for i, s in enumerate(servers)}
    count = {s: 0 for s in servers}
    out = {}
    for v in range(NUM_VSHARDS):
        o = owners[str(v)]
        if o in count and count[o] < quota[o]:
            out[str(v)] = o
            count[o] += 1
    for v in range(NUM_VSHARDS):
        if str(v) in out:
            continue
        for s in servers:
            if count[s] < quota[s]:
                out[str(v)] = s
                count[s] += 1
                break
    return out


def plan_resize(cur_map, new_servers):
    """Delta plan for moving from cur_map to a fleet of new_servers.

    Returns (new_map, moves) where moves is a list of
    (unit, src_endpoint, dst_endpoint). Dense vars keep their owner
    when it survives, else round-robin over the new fleet in sorted
    var order; sparse vshards rebalance under per-server quotas."""
    new_servers = list(new_servers)
    old_dense = cur_map.get("dense", {})
    old_sparse = cur_map.get("sparse", {})
    dense, rr = {}, 0
    for name in sorted(old_dense):
        owner = old_dense[name]
        if owner in new_servers:
            dense[name] = owner
        else:
            dense[name] = new_servers[rr % len(new_servers)]
            rr += 1
    sparse = {t: _balance_vshards(old_sparse[t], new_servers)
              for t in sorted(old_sparse)}
    moves = []
    for name in sorted(dense):
        if dense[name] != old_dense[name]:
            moves.append((dense_unit(name), old_dense[name],
                          dense[name]))
    for table in sorted(sparse):
        for v in range(NUM_VSHARDS):
            o, n = old_sparse[table][str(v)], sparse[table][str(v)]
            if o != n:
                moves.append((sparse_unit(table, v), o, n))
    new_map = {"epoch": int(cur_map.get("epoch", 0)) + 1,
               "servers": new_servers, "dense": dense,
               "sparse": sparse}
    return new_map, moves


# -- coordinator-side migration driver ------------------------------------

def _rpc(ep, kind, fields, timeout=60.0):
    """One control-plane call (client_id=0: dedup bypass; every
    migration kind is idempotent-by-state). ERR replies raise."""
    host, port = ep.rsplit(":", 1)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        wire.send_frame(s, kind, fields)
        rk, _, _, rf = wire.recv_frame(s)
    if rk == wire.ERR:
        raise MigrationError(f"{ep}: {rf[0]}")
    return rk, rf


def _split_names(blob):
    return [n for n in blob.split("\n") if n]


def inventory_map(endpoints):
    """Build the implicit epoch-0 map by asking each live server what
    it hosts (LIST_VARS — the same probe ps_probe rides)."""
    dense_owner, sparse_owner = {}, {}
    for ep in endpoints:
        rk, rf = _rpc(ep, wire.LIST_VARS, ())
        if rk != wire.OK_NAMES:
            raise MigrationError(
                f"{ep}: unexpected LIST_VARS reply kind {rk}")
        for n in _split_names(rf[0]):
            if dense_owner.setdefault(n, ep) != ep:
                raise MigrationError(
                    f"dense var {n!r} hosted on both "
                    f"{dense_owner[n]} and {ep}: static placement "
                    f"is ambiguous, refusing to build an epoch map")
        for t in _split_names(rf[1]):
            if sparse_owner.setdefault(t, ep) != ep:
                raise MigrationError(
                    f"sparse table {t!r} hosted on both "
                    f"{sparse_owner[t]} and {ep}: static placement "
                    f"is ambiguous, refusing to build an epoch map")
    return initial_map(endpoints, dense_owner, sparse_owner)


def sweep_epoch_shadows(state_dir, epoch):
    """Remove every staged shadow for an (aborted) epoch, any tag."""
    for path, _tag, ep_n, _unit in list_shadows(state_dir):
        if ep_n == int(epoch):
            try:
                os.remove(path)
            except OSError:
                pass


def _abort(state_dir, endpoints, epoch, say):
    msg = json.dumps({"epoch": int(epoch)})
    for ep in sorted(endpoints):
        try:
            _rpc(ep, wire.MIGRATE_ABORT, (msg,), timeout=10.0)
        except Exception:
            pass  # dead server: its respawn sweeps staging itself
    sweep_epoch_shadows(state_dir, epoch)
    say(f"migration to epoch {epoch} aborted; epoch {epoch - 1} "
        f"stays in force")


def run_migration(state_dir, cur_endpoints, new_endpoints, log=None,
                  rpc_timeout=120.0):
    """Drive one two-phase resize. Returns (epoch, rows_moved) on
    success; raises MigrationError after rolling back on any failure
    before the commit point. Retrying with the same arguments reuses
    the same target epoch, so a retry after an abort is idempotent."""
    say = log or (lambda m: None)
    cur_endpoints = list(cur_endpoints)
    new_endpoints = list(new_endpoints)
    cur = load_epoch_file(state_dir)
    if cur is not None:
        cur_map = dict(cur["map"], servers=cur_endpoints)
        cur_map["epoch"] = int(cur["epoch"])
    else:
        cur_map = inventory_map(cur_endpoints)
    new_map, moves = plan_resize(cur_map, new_endpoints)
    epoch = int(new_map["epoch"])
    say(f"migration to epoch {epoch}: {len(moves)} unit(s) move "
        f"({len(cur_endpoints)} -> {len(new_endpoints)} servers)")
    rows = 0
    all_eps = set(cur_endpoints) | set(new_endpoints)
    try:
        by_src = {}
        for unit, src, dst in moves:
            by_src.setdefault(src, []).append({"unit": unit, "to": dst})
        for src in sorted(by_src):
            plan = {"epoch": epoch, "units": by_src[src]}
            rk, rf = _rpc(src, wire.MIGRATE_PLAN, (json.dumps(plan),),
                          timeout=rpc_timeout)
            if rk != wire.OK_ARR:
                raise MigrationError(
                    f"source {src}: unexpected reply kind {rk}")
            rows += int(np.asarray(rf[0]).reshape(-1)[0])
        # phase-2 gate: every staged shadow must exist, verify, and
        # describe the unit we expect (the TORN-fault catch point)
        for unit, _src, dst in moves:
            p = shadow_path(state_dir, tag_of_ep(dst), epoch, unit)
            try:
                manifest, _ = ioc.verify_npz(p)
            except Exception as e:
                raise MigrationError(
                    f"staged shadow {os.path.basename(p)}: "
                    f"{type(e).__name__}: {e}")
            body = {k: v for k, v in (manifest or {}).items()
                    if k != "integrity"}
            if body.get("unit") != unit or \
                    int(body.get("epoch", -1)) != epoch:
                raise MigrationError(
                    f"staged shadow {os.path.basename(p)} describes "
                    f"{body.get('unit')!r}@{body.get('epoch')!r}, "
                    f"expected {unit!r}@{epoch}")
    except MigrationError:
        _abort(state_dir, all_eps, epoch, say)
        raise
    except Exception as e:
        _abort(state_dir, all_eps, epoch, say)
        raise MigrationError(f"{type(e).__name__}: {e}")
    # THE commit point: one atomic publish
    publish_epoch_file(state_dir, epoch, new_map)
    say(f"fleet epoch {epoch} committed ({rows} row(s) migrated)")
    commit = json.dumps({"epoch": epoch, "map": new_map})
    for ep in sorted(all_eps):
        for _attempt in range(3):
            try:
                _rpc(ep, wire.MIGRATE_COMMIT, (commit,),
                     timeout=rpc_timeout)
                break
            except Exception:
                time.sleep(0.2)
        else:
            say(f"MIGRATE_COMMIT to {ep} failed; its respawn "
                f"reconciles from {EPOCH_FILE}")
    return epoch, rows
