"""Per-rank heartbeat files — the elastic launcher's hang watchdog signal.

Contract (consumed by ``launch._supervise`` and produced by training
loops): the launcher exports ``PADDLE_HEARTBEAT_DIR`` to every child it
spawns; a child that wants hang protection touches
``<dir>/rank<PADDLE_TRAINER_ID>.hb`` at least once per watchdog period
(``auto_checkpoint`` does this automatically via ``Heartbeat.from_env``).
The launcher's wait loop reads the files' mtimes: a rank whose file
exists but has not been touched for ``--hang_timeout`` seconds is *hung*
(kill + restart the gang); a rank whose file never appeared is merely
*slow* — maybe a long startup, maybe a worker that does not heartbeat at
all — and is logged but never killed by the watchdog (the global
``timeout`` still bounds it). That asymmetry keeps ``--hang_timeout``
safe to enable for workers that never opt in.

Everything here is stdlib-only: the launcher must work without jax.
"""

import os
import re
import threading
import time

__all__ = ["Heartbeat", "heartbeat_path", "metrics_path", "last_beat",
           "stale_ranks", "silent_ranks", "reset", "sweep_stale_ranks",
           "ENV_DIR", "ENV_RANK"]

ENV_DIR = "PADDLE_HEARTBEAT_DIR"
ENV_RANK = "PADDLE_TRAINER_ID"


def heartbeat_path(dirname, rank):
    return os.path.join(dirname, f"rank{int(rank)}.hb")


def metrics_path(dirname, rank):
    """Where a rank's Prometheus snapshot lives: next to its heartbeat
    file, so the launcher finds both liveness and metrics in one place
    (written atomically by monitor.exporter.RankExporter; deliberately
    NOT cleared by reset() — a dead incarnation's last snapshot is
    evidence, not a liveness vouch)."""
    return os.path.join(dirname, f"rank{int(rank)}.prom")


class Heartbeat:
    """Touches this rank's heartbeat file; rate-limited so a tight
    training loop can call ``beat()`` every step for free.

    Use inline (``hb.beat()`` inside the loop body) or as a background
    thread (``hb.start()`` / ``hb.stop()``) for loops whose step time
    may legitimately exceed the watchdog period — note the thread
    variant only proves the *process* is alive, not the loop.
    """

    def __init__(self, dirname, rank, interval=1.0):
        self.dirname = dirname
        self.rank = int(rank)
        self.path = heartbeat_path(dirname, rank)
        self.interval = float(interval)
        self._last = None           # None: the first beat always fires
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(dirname, exist_ok=True)

    @classmethod
    def from_env(cls, env=None, interval=1.0):
        """The child-side hookup: a ``Heartbeat`` wired from the
        launcher's env, or None when not launched under a supervisor."""
        env = os.environ if env is None else env
        if not env.get(ENV_DIR):
            return None
        return cls(env[ENV_DIR], env.get(ENV_RANK, "0"), interval=interval)

    def beat(self, force=False):
        """Touch the file (rate-limited to ``interval``). Returns True
        if the file was actually touched. Never raises: a dead disk
        must not kill the training loop it is meant to protect."""
        now = time.monotonic()
        if (not force and self._last is not None
                and now - self._last < self.interval):
            return False
        self._last = now
        try:
            with open(self.path, "a"):
                pass
            os.utime(self.path, None)
        except OSError:
            return False
        return True

    # -- background-thread variant ----------------------------------------
    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            self.beat(force=True)

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        self.beat(force=True)
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


# -- launcher-side readers --------------------------------------------------
def last_beat(dirname, rank):
    """Wall-clock mtime of the rank's heartbeat file, or None if it
    never beat."""
    try:
        return os.stat(heartbeat_path(dirname, rank)).st_mtime
    except OSError:
        return None


def stale_ranks(dirname, nranks, timeout, now=None):
    """Ranks that heartbeat at least once and then stopped: list of
    (rank, age_seconds) with age > timeout. These are *hung*."""
    now = time.time() if now is None else now
    out = []
    for r in range(nranks):
        lb = last_beat(dirname, r)
        if lb is not None and now - lb > timeout:
            out.append((r, now - lb))
    return out


def silent_ranks(dirname, nranks):
    """Ranks whose heartbeat file never appeared — *slow* (or not
    heartbeating at all); the watchdog logs but does not kill these."""
    return [r for r in range(nranks) if last_beat(dirname, r) is None]


def reset(dirname, nranks):
    """Clear all heartbeat files (between gang restarts, so a dead
    incarnation's beats cannot vouch for the new one)."""
    for r in range(nranks):
        try:
            os.remove(heartbeat_path(dirname, r))
        except OSError:
            pass


_RANK_FILE_RE = re.compile(r"^rank(\d+)\.(hb|prom)$")


def sweep_stale_ranks(dirname, nranks):
    """Remove the heartbeat AND metrics files of ranks >= ``nranks`` —
    leftovers of a previous, larger incarnation. An elastic shrink
    otherwise leaves ``rank<N>.prom`` polluting the aggregated
    ``metrics.prom``/status line forever (the dead rank's counters keep
    being summed in) and a stale ``rank<N>.hb`` lying around for a
    later incarnation that grows back over the index. Unlike
    ``reset``, the ``.prom`` removal is deliberate: a rank that no
    longer EXISTS in the job is not evidence, it is noise. Scan-based
    (not ``range``) so any count of leftovers is caught. Returns the
    removed filenames."""
    removed = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return removed
    for f in names:
        m = _RANK_FILE_RE.match(f)
        if m and int(m.group(1)) >= nranks:
            try:
                os.remove(os.path.join(dirname, f))
                removed.append(f)
            except OSError:
                pass
    return sorted(removed)
