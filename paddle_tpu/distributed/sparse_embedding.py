"""Host-resident sharded sparse embeddings — the parameter-server capability.

Parity targets: the reference's large-sparse path — distributed lookup
tables served by pservers (operators/distributed/parameter_prefetch.cc,
parameter_send/recv), lookup_sparse_table_op.cc (auto-growing rows),
pserver-side per-parameter optimize blocks (listen_and_serv_op.cc RunSyncLoop),
SelectedRows sparse gradients (framework/selected_rows.h), and the async
Communicator's merge-then-push (operators/distributed/communicator.h:103
MergeVars).

TPU-first redesign: giant embeddings live in HOST RAM, sharded by id hash;
the TPU step only ever sees the dense [batch, slots, dim] slice that was
prefetched for the current batch. Gradients w.r.t. that slice come out of
the jitted step as ordinary dense arrays and are pushed back
asynchronously — the push overlaps the next step's compute, so the sparse
path never stalls the chip (the design constraint SURVEY §7 calls out).
A "shard" here is the unit a multi-host deployment would place per host;
in-process they are independent lock-protected tables, preserving the
pserver sharding semantics (round-robin/hash dispatch,
transpiler/ps_dispatcher.py) without the RPC hop.
"""

import os
import queue
import threading

import numpy as np

__all__ = ["SparseEmbeddingTable", "sparse_sgd", "sparse_adagrad"]


def _hash_ids(ids, num_shards):
    # splitmix-style mix so adjacent ids spread across shards
    x = ids.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(num_shards)).astype(np.int64)


def _hash_uniform_rows(ids, dim, seed, scale):
    """Vectorized deterministic init: per-(id, column) splitmix64 →
    uniform[-scale, scale). One numpy pass for ANY number of new ids —
    the per-id RandomState the naive form needs costs ~50us each, which
    at CTR id-churn rates (millions of new ids) dominates the step."""
    with np.errstate(over="ignore"):
        idn = np.asarray(ids, np.uint64)[:, None]
        jn = np.arange(dim, dtype=np.uint64)[None, :]
        x = (idn * np.uint64(0x9E3779B97F4A7C15)
             + (jn + np.uint64(1)) * np.uint64(0xD1B54A32D192ED03)
             + np.uint64(np.uint64(seed) * np.uint64(0x2545F4914F6CDD1D)))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    u = (x >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return ((u * 2.0 - 1.0) * scale).astype(np.float32)


class _Shard:
    """One id-hash shard: auto-growing row store + per-row optimizer slots
    (lookup_sparse_table_op.cc auto-growth; pserver optimize block state)."""

    def __init__(self, dim, initializer, seed, optimizer, grow=1024):
        self.dim = dim
        self.initializer = initializer
        self.seed = seed
        self.optimizer = optimizer
        self.index = {}                      # id -> row
        self.rows = np.zeros((0, dim), np.float32)
        self.slot = np.zeros((0, dim), np.float32)   # adagrad accumulator
        self.grow = grow
        self.lock = threading.Lock()

    def _ensure(self, ids):
        # dedupe (order-preserving): a duplicate id in one batch must not
        # claim two rows — the second claim would alias the next new id's
        # row slot
        new = list(dict.fromkeys(i for i in ids if i not in self.index))
        if not new:
            return
        need = len(self.index) + len(new)
        if need > len(self.rows):
            cap = max(need, len(self.rows) + self.grow)
            pad = cap - len(self.rows)
            self.rows = np.concatenate(
                [self.rows, np.zeros((pad, self.dim), np.float32)])
            self.slot = np.concatenate(
                [self.slot, np.zeros((pad, self.dim), np.float32)])
        r0 = len(self.index)
        for i in new:
            self.index[i] = len(self.index)
        if self.initializer is None:
            # deterministic per-id init: the same id always materialises
            # the same row, on any shard layout — one vectorized pass
            self.rows[r0:r0 + len(new)] = _hash_uniform_rows(
                np.asarray(new, np.int64), self.dim, self.seed,
                1.0 / np.sqrt(self.dim))
        else:
            # custom initializer: per-id RandomState keeps the same
            # (rng, dim) contract and per-id determinism
            for r, i in enumerate(new, start=r0):
                rng = np.random.RandomState((self.seed ^ (i * 2654435761))
                                            & 0x7FFFFFFF)
                self.rows[r] = self.initializer(rng, self.dim)

    def pull(self, ids):
        with self.lock:
            self._ensure(ids)
            rix = np.fromiter((self.index[i] for i in ids), np.int64,
                              len(ids))
            return self.rows[rix].copy()

    def push(self, ids, grads, lr):
        with self.lock:
            self._ensure(ids)
            rix = np.fromiter((self.index[i] for i in ids), np.int64,
                              len(ids))
            # the table merges to unique ids before dispatching to
            # shards — tell the builtin rules so they skip the
            # uniqueness sort; custom optimizers keep the old signature
            if self.optimizer in (sparse_sgd, sparse_adagrad):
                self.optimizer(self.rows, self.slot, rix, grads, lr,
                               unique=True)
            else:
                self.optimizer(self.rows, self.slot, rix, grads, lr)

    def state(self):
        with self.lock:
            n = len(self.index)
            ids = np.fromiter(self.index.keys(), np.int64, n)
            rix = np.fromiter(self.index.values(), np.int64, n)
            return ids, self.rows[rix].copy(), self.slot[rix].copy()

    def load(self, ids, rows, slot):
        with self.lock:
            self.index = {int(i): r for r, i in enumerate(ids)}
            self.rows = np.asarray(rows, np.float32).copy()
            self.slot = np.asarray(slot, np.float32).copy()


def _rix_unique(rix):
    if len(rix) < 2:
        return True
    s = np.sort(rix)
    return bool(np.all(s[1:] != s[:-1]))


def sparse_sgd(rows, slot, rix, grads, lr, unique=None):
    """Sparse SGD row update (pserver sgd optimize block parity).
    Unique row indices (the table's merge guarantees this, passed as
    unique=True so the hot path skips the O(n log n) confirmation) take
    the vectorized fancy-indexing path; ufunc.at only for duplicates."""
    if _rix_unique(rix) if unique is None else unique:
        rows[rix] -= lr * grads
    else:
        np.subtract.at(rows, rix, lr * grads)


def sparse_adagrad(rows, slot, rix, grads, lr, eps=1e-6, unique=None):
    """Sparse Adagrad (operators/optimizers/adagrad_op.cc SelectedRows
    kernel parity): accumulate g² per row, scale update."""
    if _rix_unique(rix) if unique is None else unique:
        slot[rix] += grads * grads
        rows[rix] -= lr * grads / (np.sqrt(slot[rix]) + eps)
    else:
        np.add.at(slot, rix, grads * grads)
        denom = np.sqrt(slot[rix]) + eps
        np.subtract.at(rows, rix, lr * grads / denom)


_OPTIMIZERS = {"sgd": sparse_sgd, "adagrad": sparse_adagrad}


class SparseEmbeddingTable:
    """Sharded, auto-growing, host-RAM embedding table with async push.

    - ``pull(ids)`` gathers dense rows (parameter_prefetch.cc parity),
      initializing unseen ids deterministically.
    - ``push(ids, grads)`` merges duplicate ids (SelectedRows merge-add,
      merge_selected_rows_op.cc) then applies the sparse optimizer.
    - ``push_async`` enqueues the push to a background thread per table —
      the caller (TPU step loop) never blocks; ``flush()`` barriers, and
      training-loop reads are safe because pull takes the shard lock.
    - ``save(dir)/load(dir)`` checkpoint shard-by-shard
      (listen_and_serv checkpoint block parity).
    """

    def __init__(self, dim, num_shards=1, initializer=None, seed=0,
                 optimizer="sgd", learning_rate=0.01):
        # initializer=None → the vectorized uniform(-1/sqrt(dim)) hash
        # init in _Shard._ensure; a custom callable keeps the
        # (rng, dim) -> row contract at per-id RandomState cost
        self.dim = dim
        self.num_shards = num_shards
        self.learning_rate = learning_rate
        opt = _OPTIMIZERS[optimizer] if isinstance(optimizer, str) \
            else optimizer
        self._opt_name = optimizer if isinstance(optimizer, str) else "custom"
        # every shard derives row init from the SAME base seed: a given id
        # materialises identically under any shard count (shard-layout
        # invariance — resharding a checkpointed table is a pure repartition)
        self.shards = [_Shard(dim, initializer, seed, opt)
                       for s in range(num_shards)]
        self._q = queue.Queue()
        self._worker = None
        self._err = None

    # -- pull ---------------------------------------------------------------
    def pull(self, ids):
        """ids: int array of any shape → rows [*ids.shape, dim]."""
        ids = np.asarray(ids, np.int64)
        flat = ids.reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        sh = _hash_ids(flat, self.num_shards)
        for s in range(self.num_shards):
            m = sh == s
            if m.any():
                out[m] = self.shards[s].pull(flat[m].tolist())
        return out.reshape(ids.shape + (self.dim,))

    # -- push ---------------------------------------------------------------
    def _merge(self, flat_ids, flat_grads):
        uniq, inv = np.unique(flat_ids, return_inverse=True)
        # per-column bincount segment-sum: vectorized C loops instead
        # of np.add.at's one-element-at-a-time scatter (~50x at 100k
        # rows; the SelectedRows merge is on the CTR hot path)
        merged = np.stack(
            [np.bincount(inv, weights=flat_grads[:, j],
                         minlength=uniq.size)
             for j in range(self.dim)], axis=1).astype(np.float32)
        return uniq, merged

    def push(self, ids, grads, learning_rate=None):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(-1, self.dim)
        lr = self.learning_rate if learning_rate is None else learning_rate
        uniq, merged = self._merge(ids, grads)
        sh = _hash_ids(uniq, self.num_shards)
        for s in range(self.num_shards):
            m = sh == s
            if m.any():
                self.shards[s].push(uniq[m].tolist(), merged[m], lr)

    def _worker_loop(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                self.push(*item)
            except Exception as e:  # surfaced on flush()
                self._err = e
            finally:
                self._q.task_done()

    def push_async(self, ids, grads, learning_rate=None):
        """Enqueue a push; returns immediately (Communicator send-thread
        parity, operators/distributed/communicator.h:160)."""
        if self._worker is None:
            self._worker = threading.Thread(target=self._worker_loop,
                                            daemon=True)
            self._worker.start()
        self._q.put((np.asarray(ids, np.int64).copy(),
                     np.asarray(grads, np.float32).copy(), learning_rate))

    def flush(self):
        """Barrier: wait until queued pushes applied (send_barrier parity)."""
        self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    # -- checkpoint ---------------------------------------------------------
    def save(self, dirname, name="sparse_table"):
        import glob
        os.makedirs(dirname, exist_ok=True)
        self.flush()
        # a re-save with fewer shards must not leave stale shard files
        # behind (load would reject or merge them)
        for f in glob.glob(os.path.join(dirname, f"{name}.shard*.npz")):
            os.remove(f)
        for s, shard in enumerate(self.shards):
            ids, rows, slot = shard.state()
            np.savez(os.path.join(dirname, f"{name}.shard{s}.npz"),
                     ids=ids, rows=rows, slot=slot)
        # manifest: lets load() tell "resharded checkpoint" apart from
        # "shard files missing" (partial copy)
        with open(os.path.join(dirname, f"{name}.manifest"), "w") as f:
            f.write(str(self.num_shards))

    def load(self, dirname, name="sparse_table"):
        """Loads a checkpoint written under ANY shard count: all shard
        files are merged and repartitioned by id hash into this table's
        layout (shard-layout invariance — resharding a checkpoint is a
        pure repartition)."""
        import glob
        self.flush()   # stale queued pushes must not land on the
                       # freshly loaded rows
        files = sorted(glob.glob(
            os.path.join(dirname, f"{name}.shard*.npz")))
        if not files:
            raise FileNotFoundError(
                f"no {name}.shard*.npz under {dirname}")
        manifest = os.path.join(dirname, f"{name}.manifest")
        if os.path.exists(manifest):
            with open(manifest) as f:
                want = int(f.read().strip())
            if len(files) != want:
                raise FileNotFoundError(
                    f"checkpoint {name} incomplete: manifest says "
                    f"{want} shard files, found {len(files)}")
        parts = [np.load(f) for f in files]
        ids = np.concatenate([p["ids"] for p in parts])
        rows = np.concatenate([p["rows"] for p in parts])
        slot = np.concatenate([p["slot"] for p in parts])
        sh = _hash_ids(ids, self.num_shards)
        for s, shard in enumerate(self.shards):
            m = sh == s
            shard.load(ids[m], rows[m], slot[m])

    @property
    def size(self):
        return sum(len(s.index) for s in self.shards)
