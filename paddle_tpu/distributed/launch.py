"""Multi-process launcher: `python -m paddle_tpu.distributed.launch`.

Parity: python/paddle/distributed/launch.py:132,214 — spawn one training
process per rank with the PADDLE_* identity env wired, stream logs,
propagate the first failure. Two modes, like the reference:

- collective (default): N trainer processes; each gets
  PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_CURRENT_ENDPOINT /
  PADDLE_TRAINER_ENDPOINTS. On a TPU pod each process drives its own
  host's chips (JAX runtime discovers topology; the env is identity
  metadata, not comm wiring — no gen_nccl_id exchange needed).
- ps (--server_num/--worker_num): pserver processes get
  TRAINING_ROLE=PSERVER + PADDLE_PSERVER_ENDPOINTS; workers get
  TRAINING_ROLE=TRAINER. Matches the reference's test_dist_base.py:429
  env contract, which role_maker.PaddleCloudRoleMaker consumes.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch_collective", "launch_ps", "find_free_ports"]


def find_free_ports(n, host="127.0.0.1"):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _spawn(cmd, env, log_prefix, log_dir):
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"{log_prefix}.log"), "wb")
    else:
        out = None
    return subprocess.Popen(cmd, env=env, stdout=out, stderr=out), out


def _wait(procs, logs, timeout=None):
    """Wait for all; on first failure terminate the rest (launch.py's
    terminate_local_procs role). Returns the worst returncode.
    ``timeout`` (seconds) kills all survivors and returns 124 — a hung
    rendezvous must not hang the caller forever."""
    deadline = None if timeout is None else time.time() + timeout
    try:
        rc = 0
        alive = dict(procs)
        while alive:
            if deadline is not None and time.time() > deadline:
                print(f"[launch] timeout after {timeout}s; killing "
                      f"{list(alive)}", file=sys.stderr)
                for q in alive.values():
                    q.kill()
                for q in alive.values():
                    q.wait()        # reap: no zombies, ports released
                return 124
            for name, p in list(alive.items()):
                r = p.poll()
                if r is None:
                    continue
                del alive[name]
                if r != 0:
                    print(f"[launch] {name} exited with code {r}",
                          file=sys.stderr)
                    rc = rc or r
                    for q in alive.values():
                        q.terminate()
            time.sleep(0.2)
        return rc
    except KeyboardInterrupt:
        for p in procs.values():
            p.send_signal(signal.SIGINT)
        raise
    finally:
        for f in logs:
            if f:
                f.close()


def launch_collective(script_args, nproc, started_port=None, ips="127.0.0.1",
                      log_dir=None, env_extra=None, timeout=None):
    host = ips.split(",")[0]
    # trainer endpoints double as the jax.distributed rendezvous in
    # collective mode (rank 0's is the coordinator, a long-lived bound
    # port) — trainer-to-trainer traffic like global_shuffle's sample
    # exchange gets its own dedicated ports, as launch_ps does. One
    # find_free_ports call for both sets: all 2*nproc sockets are
    # bound simultaneously, so the sets are guaranteed disjoint.
    # NOTE: with an explicit started_port the claimed range is
    # 2*nproc consecutive ports (trainers, then exchange) — see the
    # --started_port help text.
    if started_port is None:
        allp = find_free_ports(2 * nproc, host)
    else:
        allp = list(range(started_port, started_port + 2 * nproc))
    ports, xports = allp[:nproc], allp[nproc:]
    endpoints = ",".join(f"{host}:{p}" for p in ports)
    exchange_eps = ",".join(f"{host}:{p}" for p in xports)
    procs, logs = {}, []
    for rank in range(nproc):
        env = dict(os.environ, **(env_extra or {}))
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nproc),
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{ports[rank]}",
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_EXCHANGE_ENDPOINTS": exchange_eps,
            "TRAINING_ROLE": "TRAINER",
        })
        p, f = _spawn([sys.executable, "-u"] + script_args, env,
                      f"workerlog.{rank}", log_dir)
        procs[f"trainer {rank}"] = p
        logs.append(f)
    return _wait(procs, logs, timeout=timeout)


def launch_ps(script_args, server_num, worker_num, started_port=None,
              log_dir=None, env_extra=None, timeout=None):
    host = "127.0.0.1"
    ports = (find_free_ports(server_num, host) if started_port is None
             else list(range(started_port, started_port + server_num)))
    server_eps = ",".join(f"{host}:{p}" for p in ports)
    # trainers also get their own endpoints: trainer-to-trainer traffic
    # (global_shuffle's sample exchange) rides these in PS mode too
    wports = (find_free_ports(worker_num, host) if started_port is None
              else list(range(started_port + server_num,
                              started_port + server_num + worker_num)))
    worker_eps = ",".join(f"{host}:{p}" for p in wports)
    procs, logs = {}, []
    for i in range(server_num):
        env = dict(os.environ, **(env_extra or {}))
        env.update({
            "TRAINING_ROLE": "PSERVER",
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINERS_NUM": str(worker_num),
            "PADDLE_PSERVER_ENDPOINTS": server_eps,
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{ports[i]}",
        })
        p, f = _spawn([sys.executable, "-u"] + script_args, env,
                      f"serverlog.{i}", log_dir)
        procs[f"pserver {i}"] = p
        logs.append(f)
    for i in range(worker_num):
        env = dict(os.environ, **(env_extra or {}))
        env.update({
            "TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINERS_NUM": str(worker_num),
            "PADDLE_PSERVER_ENDPOINTS": server_eps,
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{wports[i]}",
            "PADDLE_TRAINER_ENDPOINTS": worker_eps,
        })
        p, f = _spawn([sys.executable, "-u"] + script_args, env,
                      f"workerlog.{i}", log_dir)
        procs[f"trainer {i}"] = p
        logs.append(f)
    return _wait(procs, logs, timeout=timeout)


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn one training process per rank (launch.py parity)")
    ap.add_argument("--nproc_per_node", type=int, default=None,
                    help="collective mode: trainers on this node "
                         "(default: local device count)")
    ap.add_argument("--ips", default="127.0.0.1")
    ap.add_argument("--started_port", type=int, default=None,
                    help="first port of the claimed range; collective "
                         "mode claims 2*nproc consecutive ports "
                         "(trainer endpoints, then global_shuffle "
                         "exchange endpoints)")
    ap.add_argument("--server_num", type=int, default=0,
                    help="ps mode: pserver process count")
    ap.add_argument("--worker_num", type=int, default=0,
                    help="ps mode: trainer process count")
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("training_script")
    ap.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return ap.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    script = [args.training_script] + args.training_script_args
    if args.server_num or args.worker_num:
        rc = launch_ps(script, args.server_num, max(args.worker_num, 1),
                       args.started_port, args.log_dir)
    else:
        nproc = args.nproc_per_node
        if nproc is None:
            try:
                import jax
                nproc = max(jax.local_device_count(), 1)
            except Exception:
                nproc = 1
        rc = launch_collective(script, nproc, args.started_port, args.ips,
                               args.log_dir)
    sys.exit(rc)


if __name__ == "__main__":
    main()
