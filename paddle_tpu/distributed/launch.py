"""Multi-process launcher: `python -m paddle_tpu.distributed.launch`.

Parity: python/paddle/distributed/launch.py:132,214 — spawn one training
process per rank with the PADDLE_* identity env wired, stream logs,
propagate the first failure. Two modes, like the reference:

- collective (default): N trainer processes; each gets
  PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_CURRENT_ENDPOINT /
  PADDLE_TRAINER_ENDPOINTS. On a TPU pod each process drives its own
  host's chips (JAX runtime discovers topology; the env is identity
  metadata, not comm wiring — no gen_nccl_id exchange needed).
- ps (--server_num/--worker_num): pserver processes get
  TRAINING_ROLE=PSERVER + PADDLE_PSERVER_ENDPOINTS; workers get
  TRAINING_ROLE=TRAINER. Matches the reference's test_dist_base.py:429
  env contract, which role_maker.PaddleCloudRoleMaker consumes.

Beyond the reference (elastic supervision — SURVEY §5.3 pairs
re-schedulable pod jobs with `io_checkpoint`'s "checkpoint often,
restart anywhere"): the launcher is a supervisor, not just a spawner.

- `--max_restarts N`: a failed or hung rank triggers a restart with
  exponential backoff. Collective mode restarts the whole *gang*
  (survivors would deadlock in the next collective against a dead
  peer); ps mode restarts individual workers while pservers stay up.
- `--hang_timeout S`: hang watchdog. Children touch per-rank heartbeat
  files (see `health.py`; `auto_checkpoint` does it automatically); a
  rank that beat and then stopped for S seconds is *hung* and its gang
  is killed + restarted. A rank that never beat is only logged as
  *slow* — the watchdog never kills workers that don't opt in.
- `--grace_period S`: SIGTERM to the launcher (the TPU-pod preemption
  signal) is forwarded to children, which get S seconds to flush
  (`CheckpointManager.wait()` drains pending async shards) before
  SIGKILL. The launcher then exits 143 without restarting.
- `--min_ranks / --max_ranks`: topology-elastic gangs. A rank exiting
  with code 31 ("rank departed" — spot reclaim, node repair; see
  SHRINK_RC) shrinks the next incarnation to the surviving world size
  instead of respawning a gang that can never be whole again, and
  late-joining hosts (join-request files under `<log_dir>/elastic/`)
  are admitted at the next restart boundary instead of being turned
  away. Each incarnation's world size rides to workers in
  PADDLE_TRAINERS_NUM, so `CheckpointManager.restore()` re-shards the
  last-good checkpoint onto the new mesh and the data cursor rescales
  (see io_checkpoint / docs/ELASTIC_TRAINING.md). Defaults keep
  today's fixed-gang semantics.
- `--ps_snapshot_secs S` (ps mode): pserver failover. Pservers
  snapshot their hosted state to `<log_dir>/ps_state` every S seconds
  (integrity-manifested, atomically published — see distributed/ps.py
  and docs/ELASTIC_TRAINING.md "Pserver failover"); a pserver that
  dies is respawned at its original endpoint under the --max_restarts
  budget and warm-boots from the last-good snapshot while the
  trainers' clients reconnect; with --hang_timeout the supervisor
  also probes each pserver's request loop (a LIST_VARS ping) so a
  wedged-but-alive server is detected and restarted, not just a dead
  one. Without the flag a pserver death tears the job down (today's
  semantics).

Each child additionally sees PADDLE_RESTART_COUNT (0 on the first
incarnation) and PADDLE_HEARTBEAT_DIR.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import shutil
import tempfile
import threading
import time

from paddle_tpu.core.compile_cache import ENV_VAR as CACHE_ENV_VAR
from paddle_tpu.distributed import health
from paddle_tpu.monitor import anomaly as _anomaly
from paddle_tpu.monitor import exporter as _exporter
from paddle_tpu.monitor import flight_recorder as _flight
from paddle_tpu.monitor import goodput as _goodput
from paddle_tpu.monitor import trace as _trace
from paddle_tpu.monitor.registry import REGISTRY as _REGISTRY
from paddle_tpu.monitor.registry import counter as _counter
from paddle_tpu.monitor.registry import gauge as _gauge

__all__ = ["launch_collective", "launch_ps", "find_free_ports",
           "backoff_delay", "probe_port_range", "elastic_join_dir",
           "SHRINK_RC", "MIGRATE_RC"]

PREEMPTED_RC = 143          # 128 + SIGTERM, the conventional code

#: a rank that exits with this code is PERMANENTLY DEPARTING (spot
#: reclaim, node repair — or testing.faults' PT_FAULT_SHRINK_AT_STEP,
#: which must match this value): under elastic flags the supervisor
#: restarts the gang at the reduced world size instead of respawning
#: the dead rank. Any other failure code keeps today's same-size gang
#: restart.
SHRINK_RC = 31

#: launch_ps exits with this code when a fleet-resize migration keeps
#: failing past its retry budget: every attempt rolled back to the old
#: epoch (no state was lost), the fleet still serves at its old size,
#: but the requested resize was ABANDONED — see docs/DEBUGGING.md
#: "my resize failed"
MIGRATE_RC = 41

#: the process exit-code vocabulary (docs/DEBUGGING.md table): naming
#: the cause in the supervisor log turns "code 29" into something an
#: operator can act on without grepping the test harness
EXIT_CODE_LABELS = {
    17: "non-finite trip (NonFiniteError)",
    23: "injected crash (testing.faults)",
    29: "checkpoint-corruption fault (testing.faults)",
    31: "rank departed (elastic shrink; supervisor resumes at the "
        "reduced world size)",
    37: "injected pserver crash (testing.faults; supervisor respawns "
        "it at the same endpoint, warm-booting from the last-good "
        "snapshot)",
    41: "pserver fleet resize abandoned (every migration attempt "
        "aborted + rolled back; the fleet still serves at its old "
        "epoch/size — see DEBUGGING.md 'my resize failed')",
    124: "timeout",
    137: "SIGKILLed (OOM killer or kill -9)",
    139: "segfault",
    143: "preempted (SIGTERM)",
}


def _rc_label(rc):
    # Popen returncodes for signal deaths are NEGATIVE (-9, -11, -15);
    # the operator-facing table speaks shell convention (128+signum)
    label = EXIT_CODE_LABELS.get(128 - rc if rc < 0 else rc)
    return f" [{label}]" if label else ""

#: seconds between job-status log lines / job-level metric snapshots
STATUS_INTERVAL = 15.0

# launcher-side telemetry (the supervisor's own registry; aggregated
# with the per-rank snapshots into <log_dir>/metrics.prom)
_m_restarts = _counter(
    "restarts_total",
    "Restarts: the launcher counts restarts it performed; a rank "
    "reports its own incarnation index")
_m_watchdog = _counter(
    "watchdog_trips_total",
    "Hang-watchdog kills (a rank heartbeat, then went silent past "
    "--hang_timeout)")
_m_stragglers = _counter(
    "straggler_trips_total",
    "Ranks newly flagged as stragglers by the launcher (mean step "
    "time above the skew threshold vs the median rank)")
_m_world = _gauge(
    "elastic_world_size",
    "World size of the current gang incarnation (= --nproc_per_node "
    "until --min_ranks/--max_ranks elasticity moves it: shrinks on "
    "rank departure, grows on admitted join requests)")
_m_ps_migration_aborts = _counter(
    "ps_migration_aborts_total",
    "Fleet-resize migration attempts the coordinator aborted and "
    "rolled back to the old epoch (a crashed/unresponsive server or "
    "a failed shadow verification mid-migration; the attempt is "
    "retried up to the resize retry budget)")
_m_ps_restarts = _counter(
    "ps_restarts_total",
    "Pserver processes the launcher respawned at their original "
    "endpoint after a death or a failed liveness probe (ps mode with "
    "--ps_snapshot_secs; the respawn warm-boots from the last-good "
    "snapshot)")


def _postmortem_env(log_dir):
    """Arm workers' flight recorders: PADDLE_POSTMORTEM_DIR under the
    log dir. A killed/crashed rank dumps its recent spans there (see
    monitor/flight_recorder.py); no log_dir means nowhere durable."""
    if not log_dir:
        return {}
    d = os.path.join(os.path.abspath(log_dir), "postmortem")
    os.makedirs(d, exist_ok=True)
    return {_flight.ENV_DIR: d}


def _trace_env(log_dir):
    """Arm workers' distributed tracing: PADDLE_TRACE_DIR under the
    log dir (per-rank span files land in <log_dir>/traces; see
    monitor/trace.py — tail sampling keeps the hot path cheap, so a
    supervised job traces by default). No log_dir means nowhere
    durable."""
    if not log_dir:
        return {}
    d = os.path.join(os.path.abspath(log_dir), "traces")
    os.makedirs(d, exist_ok=True)
    return {_trace.ENV_DIR: d}


def _goodput_env(log_dir):
    """Arm workers' goodput ledgers: PADDLE_GOODPUT_DIR under the log
    dir (see monitor/goodput.py — the dir also holds the launcher's
    incarnations.jsonl, the replay-watermark source). No log_dir means
    nowhere durable."""
    if not log_dir:
        return {}
    d = os.path.join(os.path.abspath(log_dir), "goodput")
    os.makedirs(d, exist_ok=True)
    return {_goodput.ENV_DIR: d}


def _record_incarnation(gp_dir, hb_dir, attempt, world, t_start,
                        status, rc, departed):
    """Append one gang-incarnation record to
    <gp_dir>/incarnations.jsonl: identity (attempt, world), lifetime,
    how it ended (status + labeled exit code), the replay watermark
    (max goodput_step across rank snapshots — the NEXT incarnation
    reads it to price replayed lost work), and each rank's per-phase
    ledger at death (tools/goodput_report.py's per-incarnation
    waterfall input). Never raises — evidence collection must not mask
    the job's exit path."""
    if not gp_dir:
        return
    try:
        snaps = _exporter.read_rank_snapshots(hb_dir)

        def _gv(samples, name):
            for (n, _pairs), v in samples.items():
                if n == name:
                    return float(v)
            return None

        last = [v for v in (_gv(s, "goodput_step")
                            for _t, s in snaps.values())
                if v is not None]
        restored = [v for v in (_gv(s, "goodput_restored_step")
                                for _t, s in snaps.values())
                    if v is not None]
        rec = {
            "incarnation": int(attempt),
            "world": int(world),
            "start": float(t_start),
            "end": time.time(),
            "status": status,
            "rc": int(rc),
            "rc_label": EXIT_CODE_LABELS.get(
                128 - rc if rc < 0 else rc),
            "departed": sorted(departed or []),
            "last_step": int(max(last)) if last else None,
            # MIN across ranks: the most-behind rank's restore point
            # prices the replayed lost work (a rank that restored
            # further ahead replays less, not more)
            "restored_step": int(min(restored)) if restored else None,
            "ranks": {
                str(r): {
                    "wall_seconds": _gv(s, "goodput_wall_seconds"),
                    "phases": _goodput.phase_seconds_of(s),
                } for r, (_t, s) in snaps.items()},
        }
        _goodput.record_incarnation(gp_dir, rec)
    except Exception as e:
        _log(f"goodput record failed (ignored): "
             f"{type(e).__name__}: {e}")


def _merge_job_trace(log_dir):
    """Clock-align and merge every rank's trace file into ONE
    Perfetto/Chrome JSON at <log_dir>/trace.json — the launcher-side
    close of the tracing loop. Never raises (evidence collection must
    not mask the job's exit code)."""
    if not log_dir:
        return None
    d = os.path.join(os.path.abspath(log_dir), "traces")
    try:
        out = _trace.merge_rank_traces(
            d, os.path.join(os.path.abspath(log_dir), "trace.json"))
    except Exception as e:
        _log(f"trace merge failed (ignored): {type(e).__name__}: {e}")
        return None
    if out:
        _log(f"job trace: {out} (per-rank spans clock-aligned and "
             f"merged; open in Perfetto / chrome://tracing)")
    return out


def _report_postmortems(log_dir, why):
    if not log_dir:
        return
    d = os.path.join(os.path.abspath(log_dir), "postmortem")
    try:
        dumps = sorted(f for f in os.listdir(d) if f.endswith(".json"))
    except OSError:
        return
    if dumps:
        _log(f"postmortem ({why}): {len(dumps)} dump(s) in {d} "
             f"(newest: {dumps[-1]})")


def _status_tick(hb_dir, log_dir, restarts, flagged_stragglers=None):
    """One supervision-loop status beat: log the aggregated job line
    (now carrying a ``health=`` field — anomaly trips + straggler
    skew, see monitor/anomaly.py) and refresh <log_dir>/metrics.prom
    from the rank snapshots. A rank newly entering straggler-hood gets
    its own log line and bumps ``straggler_trips_total``;
    ``flagged_stragglers`` is the PER-LAUNCH already-reported set (a
    module-global here would suppress reporting across sequential
    launches in one supervisor process). Never raises — a telemetry
    hiccup (disk error, a malformed snapshot a dying rank half-wrote)
    must not tear down the supervisor."""
    try:
        snaps = _exporter.read_rank_snapshots(hb_dir)
        # one job_health judgment feeds BOTH the health= field and the
        # straggler bookkeeping: two computations could disagree about
        # who is a straggler within a single tick
        health, stragglers = _anomaly.job_health(snaps)
        line = _exporter.job_status_line(hb_dir, restarts=restarts,
                                         snaps=snaps, health=health,
                                         registry=_REGISTRY)
        if line:
            _log("status " + line)
        if flagged_stragglers is not None:
            new = set(stragglers) - flagged_stragglers
            if new:
                _m_stragglers.inc(len(new))
                _log(f"straggler: rank(s) {sorted(new)} mean step "
                     f"time exceeds the skew threshold vs the median "
                     f"rank (see the health= field / "
                     f"docs/DEBUGGING.md)")
            flagged_stragglers.update(new)
        if log_dir:
            _exporter.write_job_snapshot(
                hb_dir, os.path.join(os.path.abspath(log_dir),
                                     "metrics.prom"),
                registry=_REGISTRY, snaps=snaps)
    except Exception as e:
        _log(f"status tick failed (ignored): {type(e).__name__}: {e}")


def _cache_dir_env(log_dir, env_extra):
    """Default the workers' persistent XLA compilation-cache dir under
    the log dir (one shared dir per job: cache keys are content hashes,
    so ranks and *restarted incarnations* share entries safely). This is
    what makes elastic restarts cheap — the respawned worker's step
    compiles replay from disk instead of redoing XLA. An explicit
    PADDLE_TPU_CACHE_DIR (ambient or via env_extra) wins; no log_dir
    means no cache (nowhere durable to put it)."""
    if not log_dir or os.environ.get(CACHE_ENV_VAR) \
            or (env_extra and env_extra.get(CACHE_ENV_VAR)):
        return {}
    return {CACHE_ENV_VAR: os.path.join(os.path.abspath(log_dir),
                                        "xla_cache")}


def find_free_ports(n, host="127.0.0.1"):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def probe_port_range(host, start, n, claim_desc):
    """Bind-check every port in the explicitly claimed range
    [start, start+n) and fail fast naming the full range — an explicit
    --started_port is never probed by find_free_ports, and a silent
    collision with an unrelated service surfaces as an inscrutable
    rendezvous failure much later."""
    busy = []
    for port in range(start, start + n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, port))
        except OSError:
            busy.append(port)
        finally:
            s.close()
    if busy:
        raise RuntimeError(
            f"--started_port {start}: port(s) {busy} in the claimed "
            f"range {start}..{start + n - 1} are already in use; "
            f"{claim_desc}")


def backoff_delay(attempt, base=1.0, cap=30.0):
    """Exponential restart backoff: base * 2**attempt, capped."""
    return min(cap, base * (2.0 ** max(attempt, 0)))


def elastic_join_dir(log_dir):
    """Where late-joining hosts request admission: any file named
    ``join.*`` dropped here is consumed at the next restart boundary
    and grows the gang by one rank (up to --max_ranks). File-based on
    purpose — it crosses the process boundary the same way heartbeats
    and rank snapshots do, needs no rendezvous service, and a
    provisioning script can request a join with ``touch``."""
    if not log_dir:
        return None
    return os.path.join(os.path.abspath(log_dir), "elastic")


def _take_ps_resize_request(dirname):
    """Consume (delete) the oldest pending pserver fleet-resize
    trigger (``ps_grow.*`` / ``ps_shrink.*`` — same file-based
    admission idiom as the collective gang's ``join.*``). Returns
    "grow", "shrink", or None."""
    if not dirname:
        return None
    try:
        names = sorted(os.listdir(dirname))
    except OSError:
        return None
    for n in names:
        if n.startswith("ps_grow.") or n.startswith("ps_shrink."):
            try:
                os.remove(os.path.join(dirname, n))
            except OSError:
                continue
            return "grow" if n.startswith("ps_grow.") else "shrink"
    return None


def _ps_retire_grace():
    """Seconds a shrunk-away pserver keeps serving AFTER the epoch
    commit (PT_PS_RETIRE_GRACE, default 2): in-flight client requests
    land on a live server that answers WRONG_EPOCH with the new map
    instead of a connection refusal."""
    try:
        return max(0.0, float(os.environ.get("PT_PS_RETIRE_GRACE",
                                             "2")))
    except ValueError:
        return 2.0


def _ps_resize_retries():
    """Aborted-migration retry budget before the coordinator abandons
    a resize and exits MIGRATE_RC (PT_PS_RESIZE_RETRIES, default 3)."""
    try:
        return max(1, int(os.environ.get("PT_PS_RESIZE_RETRIES", "3")))
    except ValueError:
        return 3


def _take_join_requests(join_dir, room):
    """Consume (delete) up to ``room`` pending join-request files;
    returns how many were admitted. Requests beyond the room stay
    queued for the next boundary."""
    if not join_dir or room <= 0:
        return 0
    try:
        names = sorted(f for f in os.listdir(join_dir)
                       if f.startswith("join."))
    except OSError:
        return 0
    taken = 0
    for f in names[:room]:
        try:
            os.remove(os.path.join(join_dir, f))
        except OSError:
            continue
        taken += 1
    return taken


def _spawn(cmd, env, log_prefix, log_dir, append=False):
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"{log_prefix}.log"),
                   "ab" if append else "wb")
    else:
        out = None
    return subprocess.Popen(cmd, env=env, stdout=out, stderr=out), out


def _drain(procs, grace_period, sig=signal.SIGTERM):
    """Signal every live proc, give them ``grace_period`` seconds to
    exit, SIGKILL the stragglers; reap everything (no zombies, ports
    released). Returns True if no SIGKILL was needed."""
    procs = [p for p in procs if p.poll() is None]
    for p in procs:
        try:
            p.send_signal(sig)
        except OSError:
            pass
    deadline = time.monotonic() + max(grace_period, 0.0)
    clean = True
    for p in procs:
        try:
            p.wait(timeout=max(deadline - time.monotonic(), 0.0))
        except subprocess.TimeoutExpired:
            clean = False
            p.kill()
            p.wait()
    return clean


def _install_term_handler(term):
    """Route SIGTERM (pod preemption) into ``term``; only possible from
    the main thread (in-process test callers on other threads simply
    don't get preemption forwarding). Returns an undo callable."""
    if threading.current_thread() is not threading.main_thread():
        return lambda: None
    prev = signal.signal(signal.SIGTERM, lambda s, f: term.set())
    return lambda: signal.signal(signal.SIGTERM, prev)


def _log(msg):
    print(f"[launch] {msg}", file=sys.stderr, flush=True)


def _wait_gang(procs, ranks, logs, deadline, hang_timeout, hb_dir, term,
               grace_period, log_dir=None, restarts=0,
               flagged_stragglers=None):
    """Poll one gang incarnation to completion.

    ``procs``: name -> Popen; ``ranks``: name -> heartbeat rank (absent
    = unwatched, e.g. pservers). Returns (status, rc, departed) with
    status one of "ok" | "fail" | "hung" | "timeout" | "preempted";
    ``departed`` is the sorted list of ranks whose process ended with
    SHRINK_RC ("rank departed") — counted over the WHOLE reaped gang
    after teardown, not just the first failure observed, so two hosts
    reclaimed at the same step both register and the elastic
    supervisor shrinks to the true surviving world size. On every
    status but "ok" the whole gang has already been torn down and
    reaped. Every STATUS_INTERVAL the loop logs the aggregated job
    status line and refreshes <log_dir>/metrics.prom from the rank
    snapshots.
    """
    start = time.time()
    warned_slow = False
    next_status = time.monotonic() + STATUS_INTERVAL

    def departed():
        # every proc is reaped by now (_drain or natural exit):
        # Popen.returncode is authoritative
        return sorted(ranks[n] for n, p in procs.items()
                      if n in ranks and p.returncode == SHRINK_RC)

    try:
        alive = dict(procs)
        while alive:
            if time.monotonic() >= next_status:
                next_status = time.monotonic() + STATUS_INTERVAL
                _status_tick(hb_dir, log_dir, restarts,
                             flagged_stragglers)
            if term.is_set():
                _log(f"SIGTERM: forwarding to {sorted(alive)} with "
                     f"{grace_period}s grace for checkpoint flush")
                if not _drain(alive.values(), grace_period):
                    _log("grace period expired; SIGKILLed stragglers")
                return "preempted", PREEMPTED_RC, []
            if deadline is not None and time.monotonic() > deadline:
                _log(f"timeout; killing {sorted(alive)}")
                _drain(alive.values(), grace_period)
                return "timeout", 124, []
            for name, p in list(alive.items()):
                r = p.poll()
                if r is None:
                    continue
                del alive[name]
                if r != 0:
                    _log(f"{name} exited with code {r}{_rc_label(r)}")
                    _drain(alive.values(), grace_period)
                    return "fail", r, departed()
            if hang_timeout is not None and alive:
                watched = {ranks[n] for n in alive if n in ranks}
                stale = [(r, age) for r, age in health.stale_ranks(
                    hb_dir, max(watched, default=-1) + 1, hang_timeout)
                    if r in watched]
                if stale:
                    r0, age = stale[0]
                    _m_watchdog.inc()
                    _log(f"watchdog: rank {r0} hung — last heartbeat "
                         f"{age:.1f}s ago (hang_timeout={hang_timeout}s); "
                         f"killing gang")
                    _drain(alive.values(), grace_period)
                    return "hung", 1, departed()
                if not warned_slow and time.time() - start > hang_timeout:
                    silent = [r for r in health.silent_ranks(
                        hb_dir, max(watched, default=-1) + 1)
                        if r in watched]
                    if silent:
                        _log(f"watchdog: rank(s) {silent} slow — no "
                             f"heartbeat yet {time.time() - start:.1f}s "
                             f"after gang start (not killed: only a rank "
                             f"that beat then stopped counts as hung)")
                    warned_slow = True
            time.sleep(0.2)
        return "ok", 0, []
    except KeyboardInterrupt:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        raise
    finally:
        for f in logs:
            if f:
                f.close()


def _make_hb_dir(log_dir):
    """(dir, is_tmp): a launcher-owned heartbeat dir. With a log_dir it
    lives there (inspectable, reused); otherwise a tempdir the caller
    must remove when the launch ends."""
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        d = os.path.join(log_dir, "heartbeat")
        os.makedirs(d, exist_ok=True)
        return d, False
    return tempfile.mkdtemp(prefix="pt_heartbeat_"), True


def launch_collective(script_args, nproc, started_port=None, ips="127.0.0.1",
                      log_dir=None, env_extra=None, timeout=None,
                      max_restarts=0, hang_timeout=None, grace_period=10.0,
                      min_ranks=None, max_ranks=None):
    """Supervise a gang of ``nproc`` trainers.

    ``min_ranks``/``max_ranks`` (either one set) make the gang
    ELASTIC instead of gang-fatal at a fixed size: with ``min_ranks``
    set, a rank exiting SHRINK_RC (31 — a spot reclaim / node repair
    saying goodbye) shrinks the next incarnation to the surviving
    world size (down to ``min_ranks``; below it the job gives up as
    before; with only ``max_ranks`` — grow-only elasticity — a
    departure is an ordinary failure and the gang restarts at full
    size), and pending
    join requests (files under ``<log_dir>/elastic/``, see
    ``elastic_join_dir``) are admitted at the next restart boundary up
    to ``max_ranks`` — a late-joining host grows the gang instead of
    being turned away. Restarts still draw from the one
    ``max_restarts`` budget with the same backoff. Each incarnation's
    world size is exported to every worker as PADDLE_TRAINERS_NUM (and
    the ``elastic_world_size`` gauge), which is what lets
    ``CheckpointManager.restore`` notice a topology change and
    re-shard. With neither flag set, behavior is exactly the fixed
    gang of old."""
    host = ips.split(",")[0]
    elastic = min_ranks is not None or max_ranks is not None
    # the bounds are contracts, not hints: silently clamping them
    # would let the gang shrink below (or grow past) what the operator
    # asked for — e.g. a --max_ranks below nproc overridden to nproc
    # would re-grow past the ceiling that was protecting the hosts
    if min_ranks is not None and not 1 <= min_ranks <= nproc:
        raise ValueError(
            f"--min_ranks {min_ranks} must be in [1, nproc={nproc}]")
    if max_ranks is not None and max_ranks < nproc:
        raise ValueError(
            f"--max_ranks {max_ranks} is below the starting world "
            f"size nproc={nproc} — lower --nproc_per_node instead")
    # shrink-on-departure is OPT-IN via --min_ranks: with only
    # --max_ranks (grow-only elasticity) a rank exiting SHRINK_RC is
    # an ordinary failure and the gang restarts at full size — the
    # floor stays nproc, it must not turn departures fatal
    can_shrink = min_ranks is not None
    lo = min_ranks if min_ranks is not None else nproc
    hi = max_ranks if max_ranks is not None else nproc
    # trainer endpoints double as the jax.distributed rendezvous in
    # collective mode (rank 0's is the coordinator, a long-lived bound
    # port) — trainer-to-trainer traffic like global_shuffle's sample
    # exchange gets its own dedicated ports, as launch_ps does. One
    # find_free_ports call for both sets: all 2*hi sockets are bound
    # simultaneously, so the sets are guaranteed disjoint — sized for
    # the LARGEST world this launch may grow to, so an admitted join
    # never scrambles the surviving ranks' endpoints.
    if started_port is None:
        allp = find_free_ports(2 * hi, host)
    else:
        probe_port_range(
            host, started_port, 2 * hi,
            f"collective mode claims 2*max world size = {2 * hi} "
            f"consecutive ports (trainer endpoints, then "
            f"global_shuffle exchange endpoints)")
        allp = list(range(started_port, started_port + 2 * hi))
    hb_dir, hb_tmp = _make_hb_dir(log_dir)
    cache_env = _cache_dir_env(log_dir, env_extra)
    pm_env = _postmortem_env(log_dir)
    tr_env = _trace_env(log_dir)
    gp_env = _goodput_env(log_dir)
    gp_dir = gp_env.get(_goodput.ENV_DIR)
    join_dir = elastic_join_dir(log_dir) if elastic else None
    if join_dir:
        os.makedirs(join_dir, exist_ok=True)
        _log(f"elastic: world size {nproc} (bounds {lo}..{hi}); join "
             f"requests = files named join.* in {join_dir}, admitted "
             f"at restart boundaries")
    elif elastic and hi > nproc:
        # growth was requested but there is nowhere to drop a join
        # request — say so instead of silently never growing
        _log(f"elastic: --max_ranks {hi} has no effect without "
             f"--log_dir (join requests are files under "
             f"<log_dir>/elastic/); the gang can shrink but not grow")

    def spawn_gang(attempt, world):
        ports, xports = allp[:world], allp[hi:hi + world]
        endpoints = ",".join(f"{host}:{p}" for p in ports)
        exchange_eps = ",".join(f"{host}:{p}" for p in xports)
        procs, ranks, logs = {}, {}, []
        try:
            for rank in range(world):
                env = dict(os.environ, **(env_extra or {}), **cache_env,
                           **pm_env, **tr_env, **gp_env)
                env.update({
                    "PADDLE_TRAINER_ID": str(rank),
                    "PADDLE_TRAINERS_NUM": str(world),
                    "PADDLE_CURRENT_ENDPOINT": f"{host}:{ports[rank]}",
                    "PADDLE_TRAINER_ENDPOINTS": endpoints,
                    "PADDLE_EXCHANGE_ENDPOINTS": exchange_eps,
                    "TRAINING_ROLE": "TRAINER",
                    "PADDLE_HEARTBEAT_DIR": hb_dir,
                    "PADDLE_RESTART_COUNT": str(attempt),
                    # goodput: startup = spawn stamp to ledger arming
                    _goodput.ENV_SPAWN: repr(time.time()),
                })
                p, f = _spawn([sys.executable, "-u"] + script_args, env,
                              f"workerlog.{rank}", log_dir,
                              append=attempt > 0)
                procs[f"trainer {rank}"] = p
                ranks[f"trainer {rank}"] = rank
                logs.append(f)
        except Exception:
            # a spawn failure mid-gang must not leak the ranks already
            # started (nor their log handles)
            _drain(procs.values(), grace_period)
            for f in logs:
                if f:
                    f.close()
            raise
        return procs, ranks, logs

    deadline = None if timeout is None else time.monotonic() + timeout
    term = threading.Event()
    undo = _install_term_handler(term)
    flagged_stragglers = set()          # per-launch straggler memory
    try:
        attempt = 0
        world = nproc
        gang_end = None
        _goodput.enable()
        while True:
            health.reset(hb_dir, world)
            # a previous larger incarnation's rank files would pollute
            # the aggregated metrics.prom/status line and confuse the
            # watchdog — ranks that no longer exist leave no evidence
            swept = health.sweep_stale_ranks(hb_dir, world)
            if swept:
                _log(f"swept stale rank file(s) of departed ranks: "
                     f"{swept}")
            _m_world.set(world)
            if gang_end is not None:
                # goodput: previous gang's death to this spawn, priced
                # at the NEW world size so launcher seconds and
                # rank-seconds share one denominator
                _goodput.attribute(
                    (time.time() - gang_end) * world,
                    phase="restart_downtime")
            gang_t0 = time.time()
            procs, ranks, logs = spawn_gang(attempt, world)
            status, rc, departed = _wait_gang(
                procs, ranks, logs, deadline, hang_timeout, hb_dir,
                term, grace_period, log_dir=log_dir, restarts=attempt,
                flagged_stragglers=flagged_stragglers)
            _status_tick(hb_dir, log_dir, attempt, flagged_stragglers)
            _record_incarnation(gp_dir, hb_dir, attempt, world,
                                gang_t0, status, rc, departed)
            gang_end = time.time()
            if status in ("ok", "timeout", "preempted"):
                return rc
            # the killed gang's flight-recorder dumps are the evidence
            # the restart would otherwise erase — surface them
            _report_postmortems(log_dir, f"gang {status}")
            if attempt >= max_restarts:
                if max_restarts:
                    _log(f"gang {status} (rc={rc}); restart budget "
                         f"{max_restarts} exhausted, giving up")
                return rc
            new_world = world
            if elastic:
                if departed and can_shrink:
                    # EVERY rank that ended with SHRINK_RC this
                    # incarnation is gone for good — two hosts
                    # reclaimed at the same step both count, whatever
                    # exit code the supervisor happened to see first
                    new_world -= len(departed)
                    _log(f"trainer(s) {departed} departed "
                         f"(rc={SHRINK_RC}"
                         f"{_rc_label(SHRINK_RC)}); gang shrinks "
                         f"{world} -> {new_world}")
                elif departed:
                    _log(f"trainer(s) {departed} departed "
                         f"(rc={SHRINK_RC}) but --min_ranks is not "
                         f"set; restarting at full size")
                joined = _take_join_requests(join_dir, hi - new_world)
                if joined:
                    _log(f"admitting {joined} late-joining rank(s) at "
                         f"this restart boundary: world size "
                         f"{new_world} -> {new_world + joined}")
                    new_world += joined
                if new_world < lo:
                    _log(f"world size {new_world} below --min_ranks "
                         f"{lo}; giving up")
                    return rc
            delay = backoff_delay(attempt)
            attempt += 1
            _m_restarts.inc()
            world = new_world
            # gang restart, not per-rank: surviving ranks would deadlock
            # in their next collective against the dead peer
            _log(f"gang {status} (rc={rc}); restarting gang "
                 f"{attempt}/{max_restarts} at world size {world} "
                 f"after {delay:.1f}s backoff")
            if term.wait(delay):
                return PREEMPTED_RC
            if deadline is not None and time.monotonic() > deadline:
                _log("timeout expired during restart backoff")
                return 124
    finally:
        undo()
        # the merged job timeline is evidence like the postmortems:
        # produced however the job ended (ok, budget-exhausted, killed)
        _merge_job_trace(log_dir)
        if hb_tmp:
            shutil.rmtree(hb_dir, ignore_errors=True)


def ps_probe(ep, timeout=2.0):
    """One supervisor-side pserver liveness probe: a LIST_VARS request
    over a fresh connection; True iff the server produced a well-formed
    reply within ``timeout`` (an ERR reply counts — the server
    ANSWERED). A wedged-but-alive pserver (accepting connections,
    never replying) times out here, which is exactly what
    ``hang_timeout`` cannot see from process liveness alone. The wire
    codec imports lazily (it needs numpy): the collective launcher
    keeps its stdlib-only contract, and a probe that cannot even
    import the codec returns None (probing disabled) rather than
    killing servers it cannot judge."""
    try:
        from paddle_tpu.distributed import wire
    except Exception:
        return None
    host, port = ep.rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as s:
            s.settimeout(timeout)
            wire.send_frame(s, wire.LIST_VARS, ())
            wire.recv_frame(s)
        return True
    except Exception:
        return False


class _PsWatch:
    """Per-pserver liveness bookkeeping for the supervision loop,
    mirroring the trainer watchdog's asymmetry: only a server that
    ANSWERED a probe at least once and then stopped answering for
    longer than the hang timeout is *wedged* (kill + respawn); a
    server that never answered is merely *slow* (long startup — jax
    import alone takes seconds) and is logged, never killed."""

    def __init__(self, n):
        self._last_ok = [None] * n      # monotonic time of last reply
        self._warned_slow = set()

    def observe(self, i, ok, now=None):
        now = time.monotonic() if now is None else now
        if ok:
            self._last_ok[i] = now

    def forget(self, i):
        """A respawned server starts a fresh history (its boot must
        not be judged against the dead incarnation's last answer)."""
        self._last_ok[i] = None
        self._warned_slow.discard(i)

    def wedged(self, hang_timeout, now=None):
        """[(index, seconds-since-last-answer)] past the timeout."""
        now = time.monotonic() if now is None else now
        return [(i, now - t) for i, t in enumerate(self._last_ok)
                if t is not None and now - t > hang_timeout]

    def slow(self, i):
        """True ONCE per server that never answered (for the one-shot
        slow log line)."""
        if self._last_ok[i] is None and i not in self._warned_slow:
            self._warned_slow.add(i)
            return True
        return False


def launch_ps(script_args, server_num, worker_num, started_port=None,
              log_dir=None, env_extra=None, timeout=None, max_restarts=0,
              hang_timeout=None, grace_period=10.0,
              ps_snapshot_secs=None, ps_min_servers=None,
              ps_max_servers=None):
    host = "127.0.0.1"
    if ps_max_servers is not None and ps_max_servers < server_num:
        raise ValueError(f"--ps_max_servers {ps_max_servers} < "
                         f"--server_num {server_num}")
    if ps_min_servers is not None and ps_min_servers > server_num:
        raise ValueError(f"--ps_min_servers {ps_min_servers} > "
                         f"--server_num {server_num}")
    # ports for the whole REACHABLE fleet are claimed up front: a grown
    # server's endpoint must be deterministic before it exists
    hi = max(server_num, ps_max_servers or server_num)
    lo = max(1, ps_min_servers or 1)
    if started_port is None:
        ports = find_free_ports(hi, host)
        wports = find_free_ports(worker_num, host)
    else:
        n = hi + worker_num
        probe_port_range(
            host, started_port, n,
            f"ps mode claims max_servers+worker_num = {n} consecutive "
            f"ports (pserver endpoints, then trainer exchange endpoints)")
        ports = list(range(started_port, started_port + hi))
        wports = list(range(started_port + hi, started_port + n))
    # the gang transpiles against the LAUNCH-time fleet only: ports
    # reserved for --ps_max_servers growth stay out of the endpoint
    # list, and clients discover grown servers via the epoch map
    server_eps = ",".join(f"{host}:{p}" for p in ports[:server_num])
    # trainers also get their own endpoints: trainer-to-trainer traffic
    # (global_shuffle's sample exchange) rides these in PS mode too
    worker_eps = ",".join(f"{host}:{p}" for p in wports)
    hb_dir, hb_tmp = _make_hb_dir(log_dir)
    cache_env = _cache_dir_env(log_dir, env_extra)
    pm_env = _postmortem_env(log_dir)
    tr_env = _trace_env(log_dir)
    # pserver failover (docs/ELASTIC_TRAINING.md "Pserver failover") is
    # OPT-IN via --ps_snapshot_secs: the snapshot dir under log_dir is
    # what makes a pserver death recoverable — without snapshots a
    # respawned server would serve freshly initialized parameters,
    # silently wrong training, so respawning stays off
    ps_state_dir = None
    if ps_snapshot_secs is not None:
        if ps_snapshot_secs <= 0:
            raise ValueError(
                f"--ps_snapshot_secs must be > 0, got {ps_snapshot_secs}")
        if log_dir:
            ps_state_dir = os.path.join(os.path.abspath(log_dir),
                                        "ps_state")
            os.makedirs(ps_state_dir, exist_ok=True)
            _log(f"pserver failover armed: snapshots every "
                 f"{ps_snapshot_secs:g}s to {ps_state_dir}; a dead "
                 f"pserver respawns at its endpoint and warm-boots "
                 f"from the last-good snapshot"
                 + ("" if max_restarts else
                    " (set --max_restarts to actually respawn)"))
        else:
            _log("--ps_snapshot_secs has no effect without --log_dir "
                 "(snapshots need somewhere durable); pserver "
                 "failover disabled")
    ps_elastic = ps_state_dir is not None and max_restarts > 0
    # fleet elasticity (docs/ELASTIC_TRAINING.md "Resizing the pserver
    # fleet"): grow/shrink requests arrive as ps_grow.*/ps_shrink.*
    # trigger files, and the supervisor coordinates the epoch-fenced
    # two-phase migration. Needs the snapshot dir (shadow staging +
    # fleet_epoch.json live there).
    fleet_elastic = ((ps_min_servers is not None
                      or ps_max_servers is not None)
                     and ps_state_dir is not None)
    resize_dir = None
    if fleet_elastic:
        resize_dir = elastic_join_dir(log_dir)
        os.makedirs(resize_dir, exist_ok=True)
        _log(f"pserver fleet elasticity armed: {lo} <= servers <= "
             f"{hi}; drop ps_grow.*/ps_shrink.* files in {resize_dir} "
             f"to resize (epoch-fenced two-phase migration)")
    elif ps_min_servers is not None or ps_max_servers is not None:
        _log("--ps_min_servers/--ps_max_servers need --ps_snapshot_secs "
             "and --log_dir (migration stages shadows in the snapshot "
             "dir); fleet resizing disabled")

    def spawn_server(i, attempt=0):
        env = dict(os.environ, **(env_extra or {}), **cache_env)
        env.update({
            "TRAINING_ROLE": "PSERVER",
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINERS_NUM": str(worker_num),
            "PADDLE_PSERVER_ENDPOINTS": server_eps,
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{ports[i]}",
            # run_pserver's exporter hookup: pserver-side metrics land
            # at rank<worker_num + i>.prom (offset past the trainers).
            # A DEDICATED env var, NOT PADDLE_HEARTBEAT_DIR: pservers
            # share the trainer id numbering, and handing them the
            # heartbeat env would make a role-shared script's
            # Heartbeat.from_env()/RankExporter.from_env() (the
            # documented worker hookup) clobber trainer i's files —
            # the pserver's beat could even mask a hung trainer i from
            # the watchdog
            "PT_PS_METRICS_DIR": hb_dir,
            "PADDLE_RESTART_COUNT": str(attempt),
        })
        if ps_state_dir:
            env["PT_PS_SNAPSHOT_DIR"] = ps_state_dir
            env["PT_PS_SNAPSHOT_SECS"] = str(ps_snapshot_secs)
        if fleet_elastic:
            env["PT_PS_ELASTIC"] = "1"
        return _spawn([sys.executable, "-u"] + script_args, env,
                      f"serverlog.{i}", log_dir, append=attempt > 0)

    def spawn_worker(i, attempt):
        env = dict(os.environ, **(env_extra or {}), **cache_env,
                   **pm_env, **tr_env)
        env.update({
            "TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINERS_NUM": str(worker_num),
            "PADDLE_PSERVER_ENDPOINTS": server_eps,
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{wports[i]}",
            "PADDLE_TRAINER_ENDPOINTS": worker_eps,
            # only workers heartbeat: pservers share the same
            # PADDLE_TRAINER_ID numbering, and their request loop has no
            # natural beat cadence — the watchdog watches trainers
            "PADDLE_HEARTBEAT_DIR": hb_dir,
            "PADDLE_RESTART_COUNT": str(attempt),
        })
        if fleet_elastic:
            # where a trainer (or an operator) drops resize triggers,
            # and where it can watch fleet_epoch.json for the commit
            env["PT_PS_ELASTIC_DIR"] = resize_dir
            env["PT_PS_STATE_DIR"] = ps_state_dir
        return _spawn([sys.executable, "-u"] + script_args, env,
                      f"workerlog.{i}", log_dir, append=attempt > 0)

    servers, workers, logs = {}, {}, []
    restarts = [0] * worker_num
    server_restarts = [0] * hi
    active = list(range(server_num))    # indices of the serving fleet
    flagged_stragglers = set()          # per-launch straggler memory
    # pserver liveness probe: a wedged-but-alive pserver (process up,
    # request loop stuck) stalls every trainer with nothing else to
    # notice it. Armed only when BOTH the hang watchdog and failover
    # are on: killing a slow-but-recoverable server is only an
    # improvement when a warm-booting respawn follows — without
    # --ps_snapshot_secs a probe kill would turn a survivable stall
    # into job teardown, changing pre-failover --hang_timeout
    # semantics
    ps_watch = (_PsWatch(hi)
                if hang_timeout is not None and server_num
                and ps_elastic else None)
    ps_probe_interval = (max(0.5, min(hang_timeout / 3.0, 5.0))
                         if ps_watch else None)
    # probes run serially inside the ONE supervision loop, and only a
    # WEDGED server pays its full timeout (a healthy one answers in
    # ms, a dead one refuses instantly) — so the per-probe timeout is
    # divided by the server count to bound the worst-case loop stall
    # (all servers wedged) at ~hang_timeout/4 per round, keeping
    # trainer reaping / respawn timers / the global deadline serviced
    ps_probe_timeout = (
        max(0.2, min(2.0, hang_timeout / (4.0 * max(server_num, 1))))
        if ps_watch else None)
    next_ps_probe = (time.monotonic() + ps_probe_interval
                     if ps_watch else None)
    health.reset(hb_dir, worker_num)    # a reused log_dir must not
                                        # vouch for the new run
    deadline = None if timeout is None else time.monotonic() + timeout
    term = threading.Event()
    # handler first, spawning inside the try: a spawn failure mid-gang
    # or a SIGTERM in the spawn window must still drain the children
    # already running
    undo = _install_term_handler(term)
    started = time.time()
    warned_slow = False

    def all_procs():
        return list(servers.values()) + list(workers.values())

    # worker idx -> monotonic respawn time: backoff never blocks the
    # supervision loop (a sleeping supervisor would miss pserver
    # deaths, other workers' faults, preemption, and the global
    # deadline for up to the backoff cap)
    pending_respawn = {}
    # pserver idx -> monotonic respawn time (same non-blocking idiom)
    pending_ps_respawn = {}
    # one in-flight fleet-resize request: {"kind", "attempts", "due"}
    pending_resize = None

    def do_resize(kind):
        """One epoch-fenced migration attempt (grow appends index
        len(active), shrink retires max(active)). Returns None on
        success; on any failure the migration has already rolled back
        to the old epoch and the failure description is returned."""
        from paddle_tpu.distributed import membership
        cur_eps = [f"{host}:{ports[i]}" for i in active]
        if kind == "grow":
            ni = len(active)
            name = f"pserver {ni}"
            if name not in servers or servers[name].poll() is not None:
                p, f = spawn_server(ni, server_restarts[ni])
                servers[name] = p
                logs.append(f)
            new_ep = f"{host}:{ports[ni]}"
            ready_by = time.monotonic() + 20.0
            while True:
                ok = ps_probe(new_ep, timeout=1.0)
                if ok:
                    break
                if ok is None:
                    # no wire codec in the launcher process means the
                    # migration RPCs below cannot run either
                    return ("wire codec unavailable in the launcher "
                            "process; fleet resize needs it")
                if servers[name].poll() is not None:
                    return f"new pserver {ni} died while booting"
                if time.monotonic() > ready_by:
                    return f"new pserver {ni} not serving after 20s"
                time.sleep(0.25)
            new_eps = cur_eps + [new_ep]
        else:
            ni = max(active)
            new_eps = [f"{host}:{ports[i]}" for i in active
                       if i != ni]
        # every participant must be SERVING (not merely alive) before
        # the migration RPCs start: a respawned-but-still-booting
        # server would otherwise burn a whole retry attempt
        ready_by = time.monotonic() + 20.0
        for ep in sorted(set(cur_eps) | set(new_eps)):
            while not ps_probe(ep, timeout=1.0):
                if time.monotonic() > ready_by:
                    return f"pserver {ep} not serving; resize needs " \
                           f"the whole fleet reachable"
                time.sleep(0.25)
        try:
            epoch, rows = membership.run_migration(
                ps_state_dir, cur_eps, new_eps, log=_log)
        except membership.MigrationError as e:
            return str(e)
        if kind == "grow":
            active.append(ni)
        else:
            active.remove(ni)
            # retire grace: clients still routed at the old epoch
            # learn the committed map via WRONG_EPOCH (or the
            # EPOCH_MAP probe once this endpoint refuses) — give the
            # in-flight requests a moment before the refusals start
            time.sleep(_ps_retire_grace())
            p = servers.pop(f"pserver {ni}", None)
            if p is not None:
                _drain([p], grace_period)
            pending_ps_respawn.pop(ni, None)
            if ps_watch:
                ps_watch.forget(ni)
        # the PS analog of the trainer-side sweep_stale_ranks: a
        # retired server's rank<worker_num+i>.hb/.prom files must not
        # linger in the metrics.prom aggregate
        health.sweep_stale_ranks(hb_dir, worker_num + len(active))
        _log(f"pserver fleet resize '{kind}' committed at epoch "
             f"{epoch}: now {len(active)} server(s), {rows} row(s) "
             f"migrated")
        return None

    def fail_server(i, why):
        """Pserver restart policy (only reachable with failover armed):
        respawn pserver i at the SAME endpoint after backoff — the
        respawned process warm-boots from the last-good snapshot and
        the trainers' clients reconnect — until the per-server budget
        is spent; then tear down the whole job (its hosted state is
        gone past recovery)."""
        if server_restarts[i] >= max_restarts:
            _log(f"pserver {i} {why}; restart budget {max_restarts} "
                 f"exhausted, tearing down the job")
            _drain(all_procs(), grace_period)
            return False
        delay = backoff_delay(server_restarts[i])
        server_restarts[i] += 1
        _m_ps_restarts.inc()
        _log(f"pserver {i} {why}; respawning at {host}:{ports[i]} "
             f"{server_restarts[i]}/{max_restarts} after {delay:.1f}s "
             f"backoff (warm boot from {ps_state_dir})")
        pending_ps_respawn[i] = time.monotonic() + delay
        if ps_watch:
            ps_watch.forget(i)
        return True

    def fail_worker(i, why):
        """Individual-worker restart policy: respawn worker i after
        backoff while the pservers (whose hosted state would be lost in
        a gang restart) stay up; give up once the budget is spent."""
        if restarts[i] >= max_restarts:
            if max_restarts:
                _log(f"trainer {i} {why}; restart budget {max_restarts} "
                     f"exhausted, tearing down the job")
            _drain(all_procs(), grace_period)
            return False
        delay = backoff_delay(restarts[i])
        restarts[i] += 1
        _m_restarts.inc()
        _report_postmortems(log_dir, f"trainer {i} {why}")
        _log(f"trainer {i} {why}; restarting worker "
             f"{restarts[i]}/{max_restarts} after {delay:.1f}s backoff "
             f"(pservers stay up)")
        pending_respawn[i] = time.monotonic() + delay
        return True

    try:
        try:
            for i in range(server_num):
                p, f = spawn_server(i)
                servers[f"pserver {i}"] = p
                logs.append(f)
            for i in range(worker_num):
                p, f = spawn_worker(i, 0)
                workers[i] = p
                logs.append(f)
        except Exception:
            _drain(all_procs(), grace_period)
            raise
        rc = 0
        done_workers = set()
        next_status = time.monotonic() + STATUS_INTERVAL
        while servers or (set(workers) - done_workers):
            if time.monotonic() >= next_status:
                next_status = time.monotonic() + STATUS_INTERVAL
                _status_tick(hb_dir, log_dir, sum(restarts),
                             flagged_stragglers)
            if term.is_set():
                live = [n for n, p in servers.items() if p.poll() is None]
                live += [f"trainer {i}" for i, p in workers.items()
                         if p.poll() is None]
                _log(f"SIGTERM: forwarding to {live} with "
                     f"{grace_period}s grace for checkpoint flush")
                if not _drain(all_procs(), grace_period):
                    _log("grace period expired; SIGKILLed stragglers")
                return PREEMPTED_RC
            if deadline is not None and time.monotonic() > deadline:
                _log("timeout; killing survivors")
                _drain(all_procs(), grace_period)
                return 124
            for name, p in list(servers.items()):
                r = p.poll()
                if r is None:
                    continue
                del servers[name]
                if r != 0:
                    _log(f"{name} exited with code {r}{_rc_label(r)}")
                    i = int(name.rsplit(None, 1)[-1])
                    if ps_elastic:
                        if not fail_server(i, f"died (rc={r})"):
                            return r
                        continue
                    # without snapshots a dead pserver loses hosted
                    # state no worker restart can recover — fail fast
                    _drain(all_procs(), grace_period)
                    return r
            for i, due in list(pending_ps_respawn.items()):
                if time.monotonic() < due:
                    continue
                del pending_ps_respawn[i]
                p, f = spawn_server(i, server_restarts[i])
                servers[f"pserver {i}"] = p
                logs.append(f)
            if fleet_elastic and pending_resize is None:
                kind = _take_ps_resize_request(resize_dir)
                if kind == "grow" and len(active) >= hi:
                    _log(f"ignoring pserver grow request: already at "
                         f"--ps_max_servers ({hi})")
                elif kind == "shrink" and len(active) <= lo:
                    _log(f"ignoring pserver shrink request: already "
                         f"at --ps_min_servers ({lo})")
                elif kind:
                    pending_resize = {"kind": kind, "attempts": 0,
                                      "due": time.monotonic()}
                    _log(f"pserver fleet resize requested: {kind} "
                         f"(currently {len(active)} server(s))")
            if (pending_resize is not None
                    and time.monotonic() >= pending_resize["due"]
                    and not pending_ps_respawn
                    and all(p.poll() is None
                            for p in servers.values())):
                err = do_resize(pending_resize["kind"])
                if err is None:
                    pending_resize = None
                else:
                    # every failed attempt already rolled back to the
                    # old epoch — nothing is lost, only not-yet-resized
                    _m_ps_migration_aborts.inc()
                    pending_resize["attempts"] += 1
                    budget = _ps_resize_retries()
                    if pending_resize["attempts"] >= budget:
                        _log(f"pserver fleet resize "
                             f"'{pending_resize['kind']}' ABANDONED "
                             f"after {budget} aborted attempt(s) "
                             f"(last: {err}); tearing down "
                             f"[exit {MIGRATE_RC}]")
                        _drain(all_procs(), grace_period)
                        return MIGRATE_RC
                    delay = backoff_delay(pending_resize["attempts"])
                    _log(f"pserver fleet resize attempt "
                         f"{pending_resize['attempts']}/{budget} "
                         f"aborted + rolled back ({err}); retrying "
                         f"in {delay:.1f}s")
                    pending_resize["due"] = time.monotonic() + delay
            if ps_watch is not None and time.monotonic() >= next_ps_probe:
                next_ps_probe = time.monotonic() + ps_probe_interval
                for i in list(active):
                    p = servers.get(f"pserver {i}")
                    if (p is None or p.poll() is not None
                            or i in pending_ps_respawn):
                        continue
                    ok = ps_probe(f"{host}:{ports[i]}",
                                  timeout=ps_probe_timeout)
                    if ok is None:      # codec unavailable: disabled
                        ps_watch = None
                        _log("pserver liveness probe disabled (wire "
                             "codec unavailable in the launcher "
                             "process)")
                        break
                    ps_watch.observe(i, ok)
                for i, age in (ps_watch.wedged(hang_timeout)
                               if ps_watch else []):
                    p = servers.get(f"pserver {i}")
                    if p is None or p.poll() is not None:
                        continue
                    _m_watchdog.inc()
                    _log(f"watchdog: pserver {i} wedged — answered "
                         f"its liveness probe, then stopped for "
                         f"{age:.1f}s (hang_timeout={hang_timeout}s); "
                         f"killing it")
                    # no grace: a wedged request loop won't act on
                    # SIGTERM; the death is handled next poll
                    # (respawn under the budget, or fail fast)
                    _drain([p], 0.0)
                    ps_watch.forget(i)
                if ps_watch:
                    for i in list(active):
                        p = servers.get(f"pserver {i}")
                        if (p is not None and p.poll() is None
                                and i not in pending_ps_respawn
                                and time.time() - started > hang_timeout
                                and ps_watch.slow(i)):
                            _log(f"watchdog: pserver {i} slow — no "
                                 f"probe reply yet (not killed: only "
                                 f"a server that answered then "
                                 f"stopped counts as wedged)")
            for i, due in list(pending_respawn.items()):
                if time.monotonic() < due:
                    continue
                del pending_respawn[i]
                try:
                    os.remove(health.heartbeat_path(hb_dir, i))
                except OSError:
                    pass
                p, f = spawn_worker(i, restarts[i])
                workers[i] = p
                logs.append(f)
            for i, p in list(workers.items()):
                if i in done_workers or i in pending_respawn:
                    continue
                r = p.poll()
                if r is None:
                    continue
                if r == 0:
                    done_workers.add(i)
                    continue
                _log(f"trainer {i} exited with code {r}{_rc_label(r)}")
                if not fail_worker(i, f"failed (rc={r})"):
                    return r
            if hang_timeout is not None:
                alive_w = [i for i, p in workers.items()
                           if p.poll() is None and i not in done_workers]
                stale = [(r, age) for r, age in health.stale_ranks(
                    hb_dir, worker_num, hang_timeout) if r in alive_w]
                if stale:
                    i, age = stale[0]
                    _m_watchdog.inc()
                    _log(f"watchdog: trainer {i} hung — last heartbeat "
                         f"{age:.1f}s ago (hang_timeout={hang_timeout}s); "
                         f"killing worker")
                    # no grace: a hung worker won't act on SIGTERM, and
                    # waiting would stall the supervision of everyone
                    # else (the invariant pending_respawn preserves)
                    _drain([workers[i]], 0.0)
                    if not fail_worker(i, f"hung ({age:.1f}s without "
                                          f"heartbeat)"):
                        return 1
                elif not warned_slow and time.time() - started > hang_timeout:
                    silent = [r for r in health.silent_ranks(
                        hb_dir, worker_num) if r in alive_w]
                    if silent:
                        _log(f"watchdog: trainer(s) {silent} slow — no "
                             f"heartbeat yet (not killed: only a rank "
                             f"that beat then stopped counts as hung)")
                    warned_slow = True
            time.sleep(0.2)
        _status_tick(hb_dir, log_dir, sum(restarts),
                     flagged_stragglers)
        return rc
    except KeyboardInterrupt:
        for p in all_procs():
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        raise
    finally:
        undo()
        _merge_job_trace(log_dir)
        if hb_tmp:
            shutil.rmtree(hb_dir, ignore_errors=True)
        for f in logs:
            if f:
                f.close()


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn one training process per rank (launch.py "
                    "parity) with elastic supervision")
    ap.add_argument("--nproc_per_node", type=int, default=None,
                    help="collective mode: trainers on this node "
                         "(default: local device count)")
    ap.add_argument("--ips", default="127.0.0.1")
    ap.add_argument("--started_port", type=int, default=None,
                    help="first port of the claimed range; collective "
                         "mode claims 2*nproc consecutive ports "
                         "(trainer endpoints, then global_shuffle "
                         "exchange endpoints). The full range is "
                         "bind-probed up front and the launch fails "
                         "fast on any collision.")
    ap.add_argument("--server_num", type=int, default=0,
                    help="ps mode: pserver process count")
    ap.add_argument("--worker_num", type=int, default=0,
                    help="ps mode: trainer process count")
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--max_restarts", type=int, default=0,
                    help="restart budget for failed/hung ranks with "
                         "exponential backoff: collective mode restarts "
                         "the whole gang, ps mode restarts individual "
                         "workers (per-worker budget) while pservers "
                         "stay up")
    ap.add_argument("--min_ranks", type=int, default=None,
                    help="collective mode: make the gang elastic — a "
                         "rank exiting with code 31 (rank departed: "
                         "spot reclaim / node repair) shrinks the next "
                         "incarnation to the surviving world size, "
                         "down to this floor (below it the job gives "
                         "up). Default: fixed gang (today's "
                         "semantics). Workers see the incarnation's "
                         "world size in PADDLE_TRAINERS_NUM; restore() "
                         "re-shards checkpoints across the change.")
    ap.add_argument("--max_ranks", type=int, default=None,
                    help="collective mode: admit late-joining ranks at "
                         "the next restart boundary, growing the gang "
                         "up to this ceiling — a join is requested by "
                         "dropping a file named join.<anything> in "
                         "<log_dir>/elastic/. Default: fixed gang.")
    ap.add_argument("--ps_snapshot_secs", type=float, default=None,
                    help="ps mode: arm pserver failover — each pserver "
                         "snapshots its hosted state (integrity-"
                         "manifested, atomically published) to "
                         "<log_dir>/ps_state every this many seconds "
                         "on a background thread, a dead pserver is "
                         "respawned at its endpoint under the "
                         "--max_restarts budget and warm-boots from "
                         "the last-good snapshot, and (with "
                         "--hang_timeout) a wedged-but-alive pserver "
                         "is probe-detected and restarted too. "
                         "Default: off (a pserver death tears the job "
                         "down, today's semantics). See "
                         "docs/ELASTIC_TRAINING.md 'Pserver failover'.")
    ap.add_argument("--ps_min_servers", type=int, default=None,
                    help="ps mode: arm pserver fleet elasticity — the "
                         "fleet may shrink down to this floor via "
                         "epoch-fenced live migration (requires "
                         "--ps_snapshot_secs + --log_dir). A shrink is "
                         "requested by dropping a file named "
                         "ps_shrink.<anything> in <log_dir>/elastic/. "
                         "Default: fixed fleet.")
    ap.add_argument("--ps_max_servers", type=int, default=None,
                    help="ps mode: allow the fleet to grow up to this "
                         "ceiling (ports for the whole range are "
                         "claimed up front; a grow is requested via a "
                         "ps_grow.<anything> file in "
                         "<log_dir>/elastic/). Each resize is a "
                         "two-phase migration that rolls back on any "
                         "failure; after PT_PS_RESIZE_RETRIES aborted "
                         "attempts the job exits 41. See "
                         "docs/ELASTIC_TRAINING.md 'Resizing the "
                         "pserver fleet'.")
    ap.add_argument("--hang_timeout", type=float, default=None,
                    help="hang watchdog: kill+restart a gang whose rank "
                         "heartbeat once and then stopped for this many "
                         "seconds (see distributed/health.py; "
                         "auto_checkpoint heartbeats automatically)")
    ap.add_argument("--grace_period", type=float, default=10.0,
                    help="seconds between SIGTERM (forwarded on "
                         "launcher preemption, or sent before any "
                         "teardown) and SIGKILL — the window for "
                         "CheckpointManager.wait() to flush")
    ap.add_argument("--timeout", type=float, default=None,
                    help="global wall-clock budget across all restarts; "
                         "exceeded -> kill everything, exit 124")
    ap.add_argument("training_script")
    ap.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return ap.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    script = [args.training_script] + args.training_script_args
    if args.server_num or args.worker_num:
        rc = launch_ps(script, args.server_num, max(args.worker_num, 1),
                       args.started_port, args.log_dir,
                       timeout=args.timeout,
                       max_restarts=args.max_restarts,
                       hang_timeout=args.hang_timeout,
                       grace_period=args.grace_period,
                       ps_snapshot_secs=args.ps_snapshot_secs,
                       ps_min_servers=args.ps_min_servers,
                       ps_max_servers=args.ps_max_servers)
    else:
        nproc = args.nproc_per_node
        if nproc is None:
            try:
                import jax
                nproc = max(jax.local_device_count(), 1)
            except Exception:
                nproc = 1
        rc = launch_collective(script, nproc, args.started_port, args.ips,
                               args.log_dir, timeout=args.timeout,
                               max_restarts=args.max_restarts,
                               hang_timeout=args.hang_timeout,
                               grace_period=args.grace_period,
                               min_ranks=args.min_ranks,
                               max_ranks=args.max_ranks)
    sys.exit(rc)


if __name__ == "__main__":
    main()
